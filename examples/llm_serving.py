"""LLM serving: stream tokens from a GPT-style decoder through the
paged-KV continuous-batching engine, 8 concurrent clients with mixed
prompt lengths, TTFT/TPOT summary (paddle_tpu/serving_llm; wire spec
in docs/serving_protocol.md, "Streaming generation").

The point to watch in the output: short prompts that arrive while a
long prompt is mid-decode still get fast first tokens — admission is
continuous, not batch-synchronous.

``--speculative`` runs the same workload with speculative decoding on
(FLAGS_speculative_k, self-drafting so the accept rate is exactly 1.0
at temperature 0) and prints the accept rate alongside TTFT/TPOT —
the CPU-visible proof that drafts verify and commit without changing
a single output token.

``--router`` puts the front-door router (serving_llm/router.py) over
TWO backends, stops the one actively serving a sampled stream after
two delivered tokens, and shows the client-visible sequence is
bitwise identical to an uninterrupted reference (position-keyed
sampling + sample_offset resume; docs/fault_tolerance.md, "Router
failover taxonomy") — then keeps serving the concurrent workload on
the survivor through the same front door.

``--tenants`` turns on the multi-tenant traffic plane
(FLAGS_tenant_fair_share; docs/fault_tolerance.md, "Tenant
isolation"): a bulk flood shares one engine with premium clients
that arrive AFTER the flood is resident, and the per-class TTFT
summary shows weighted fair share keeping premium first tokens fast
while bulk absorbs the queueing. Every stream still finishes — fair
share reorders, it never starves (weight floor).
"""

from __future__ import annotations

import sys
import threading
import time

import numpy as np


def _percentile(xs, q):
    # shared estimator so the example's numbers agree with the
    # SLO/report planes (observability/metrics.py)
    from paddle_tpu.observability import metrics as _m
    return _m.percentile(xs, q)


def main(n_clients: int = 8, max_new_tokens: int = 8,
         verbose: bool = True, speculative: bool = False,
         router: bool = False, tenants: bool = False):
    import paddle_tpu as pt
    from paddle_tpu.models import GPTLanguageModel
    from paddle_tpu.serving_llm import LLMEngine

    model = GPTLanguageModel()
    if router:
        return _run_router(model, n_clients, max_new_tokens, verbose)
    if tenants:
        return _run_tenants(model, n_clients, max_new_tokens, verbose)
    if speculative:
        pt.set_flags({"speculative_k": 4})
        engine = LLMEngine(model, block_size=16, pool_blocks=64,
                           draft_model=model)
    else:
        engine = LLMEngine(model, block_size=16, pool_blocks=64)
    try:
        return _run(engine, n_clients, max_new_tokens, verbose,
                    speculative)
    finally:
        if speculative:
            pt.set_flags({"speculative_k": 0})


def _run(engine, n_clients, max_new_tokens, verbose, speculative):
    from paddle_tpu.inference import Client, Server

    model = engine.model
    rng = np.random.default_rng(0)
    # mixed prompt lengths: half short chat-style, half long-context
    prompts = [rng.integers(0, model.config.vocab_size,
                            size=(4 if i % 2 else 48)).astype(np.int32)
               for i in range(n_clients)]
    results = [None] * n_clients

    def run_client(i):
        with Client(port=srv.port, timeout_s=120.0) as cli:
            t0 = time.perf_counter()
            stamps, toks = [], []
            for chunk in cli.generate_stream(
                    prompts[i], max_new_tokens=max_new_tokens):
                stamps.append(time.perf_counter())
                toks.append(int(chunk[0]))
            gaps = [b - a for a, b in zip(stamps, stamps[1:])]
            results[i] = {
                "tokens": toks,
                "ttft_ms": (stamps[0] - t0) * 1e3,
                "tpot_ms": (sum(gaps) / len(gaps)) * 1e3 if gaps
                else 0.0,
            }

    with Server(None, llm_engine=engine) as srv:
        threads = [threading.Thread(target=run_client, args=(i,))
                   for i in range(n_clients)]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=300)
        wall_s = time.perf_counter() - t0

    assert all(r is not None and len(r["tokens"]) == max_new_tokens
               for r in results), results
    assert engine.allocator.num_used == 0     # every block returned
    engine.allocator.check()
    n_tokens = sum(len(r["tokens"]) for r in results)
    ttfts = [r["ttft_ms"] for r in results]
    tpots = [r["tpot_ms"] for r in results if r["tpot_ms"] > 0]
    summary = {
        "ok": True,
        "clients": n_clients,
        "tokens": n_tokens,
        "tokens_per_s": n_tokens / wall_s,
        "ttft_p50_ms": _percentile(ttfts, 50),
        "ttft_p99_ms": _percentile(ttfts, 99),
        "tpot_p50_ms": _percentile(tpots, 50),
        "preemptions": engine.scheduler.preemptions_total,
    }
    if speculative:
        # self-drafting at temperature 0: anything below 1.0 means the
        # verify/commit path changed a token it should not have
        accept_rate = (engine.spec_accepted_total
                       / engine.spec_proposed_total
                       if engine.spec_proposed_total else 0.0)
        assert accept_rate == 1.0, accept_rate
        summary["accept_rate"] = accept_rate
        summary["proposed_tokens"] = engine.spec_proposed_total
    if verbose:
        mode = " [speculative]" if speculative else ""
        print(f"llm_serving{mode}: {n_clients} concurrent streaming "
              f"clients, {n_tokens} tokens in {wall_s:.2f}s "
              f"({summary['tokens_per_s']:.1f} tok/s aggregate)")
        print(f"  TTFT p50={summary['ttft_p50_ms']:.1f}ms "
              f"p99={summary['ttft_p99_ms']:.1f}ms | "
              f"TPOT p50={summary['tpot_p50_ms']:.1f}ms | "
              f"KV pool clean, "
              f"preemptions={summary['preemptions']}")
        if speculative:
            print(f"  speculative: accept rate "
                  f"{summary['accept_rate']:.2f} over "
                  f"{summary['proposed_tokens']} proposed draft "
                  f"tokens (self-draft, temp 0 — must be 1.00)")
        for i, r in enumerate(results):
            kind = "short" if i % 2 else "long "
            print(f"  client {i} ({kind}, {len(prompts[i])} prompt "
                  f"tokens): ttft={r['ttft_ms']:.1f}ms "
                  f"tokens={r['tokens'][:4]}...")
    return summary


def _run_router(model, n_clients, max_new_tokens, verbose):
    import paddle_tpu as pt
    from paddle_tpu.inference import Client, Server
    from paddle_tpu.serving_llm import LLMEngine
    from paddle_tpu.serving_llm.router import Router

    pt.set_flags({"router_retry_backoff_s": 0.0})
    eng_a = LLMEngine(model, block_size=16, pool_blocks=64)
    eng_b = LLMEngine(model, block_size=16, pool_blocks=64)
    srv_a = Server(None, llm_engine=eng_a)
    srv_b = Server(None, llm_engine=eng_b)
    prompt = np.arange(6, dtype=np.int32) * 7 % model.config.vocab_size
    kw = dict(max_new_tokens=max(max_new_tokens, 6), temperature=0.8,
              seed=7)

    # the uninterrupted reference, straight off backend A
    with Client(port=srv_a.port, timeout_s=120.0,
                deadline_s=120.0) as cli:
        ref = [int(c[0]) for c in cli.generate_stream(prompt, **kw)]

    fo_router = Router([("127.0.0.1", srv_a.port),
                        ("127.0.0.1", srv_b.port)],
                       probe_interval_s=0.3).start()
    try:
        # stream through the front door; stop the backend actively
        # serving it after two delivered tokens. Decode is paced so
        # the stream is still mid-flight when the stop lands — a fast
        # warm engine can otherwise buffer every chunk before the
        # client reads the second one
        pt.set_flags({"fault_spec": "llm_decode:sleep=100"})
        try:
            got, victim = [], None
            with Client(port=fo_router.port, timeout_s=120.0,
                        deadline_s=120.0) as cli:
                for i, chunk in enumerate(cli.generate_stream(prompt,
                                                              **kw)):
                    got.append(int(chunk[0]))
                    if i == 1:
                        busy = [b for b in
                                fo_router.snapshot()["backends"]
                                if b["streams_active"] > 0]
                        port = int(busy[0]["name"].rsplit(":", 1)[1])
                        victim = srv_a if port == srv_a.port else srv_b
                        victim.stop()
        finally:
            pt.set_flags({"fault_spec": ""})
        assert got == ref, (got, ref)  # bitwise, at temperature 0.8
        snap = fo_router.snapshot()
        assert snap["failovers_total"] == 1, snap

        # the survivor keeps serving the concurrent workload through
        # the same front door
        results = [None] * n_clients

        def run_client(i):
            with Client(port=fo_router.port, timeout_s=120.0,
                        deadline_s=120.0) as cli:
                toks = [int(c[0]) for c in cli.generate_stream(
                    prompt, max_new_tokens=max_new_tokens)]
                results[i] = toks

        threads = [threading.Thread(target=run_client, args=(i,))
                   for i in range(n_clients)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=300)
        assert all(r is not None and len(r) == max_new_tokens
                   for r in results), results
        summary = {
            "ok": True,
            "clients": n_clients,
            "tokens": len(got) + sum(len(r) for r in results),
            "failovers": snap["failovers_total"],
            "shed": snap["shed_total"],
            "victim_state": next(
                b["state"] for b in fo_router.snapshot()["backends"]
                if b["streams_active"] == 0 and b["state"] != "closed"),
        }
    finally:
        fo_router.stop()
        for srv in (srv_a, srv_b):
            try:
                srv.stop()
            # ptlint: disable=silent-failure -- the failover victim is already stopped
            except Exception:
                pass
        pt.set_flags({"router_retry_backoff_s": 0.05})
    assert eng_a.allocator.num_used == 0
    assert eng_b.allocator.num_used == 0
    if verbose:
        print(f"llm_serving [router]: mid-stream backend stop after "
              f"2 tokens — spliced stream == reference bitwise at "
              f"temperature 0.8 ({len(got)} tokens, "
              f"{summary['failovers']} failover)")
        print(f"  survivor then served {n_clients} concurrent "
              f"clients through the same front door; victim state: "
              f"{summary['victim_state']}; KV pools clean")
    return summary


def _run_tenants(model, n_clients, max_new_tokens, verbose):
    import paddle_tpu as pt
    from paddle_tpu import observability as obs
    from paddle_tpu.inference import Client, Server
    from paddle_tpu.serving_llm import LLMEngine

    n_bulk = n_clients
    n_prem = max(2, n_clients // 2)
    # metrics on for the per-tenant admission counters in the summary
    metrics_were_on = pt.get_flags(["enable_metrics"])["enable_metrics"]
    pt.set_flags({"tenant_fair_share": True,
                  "tenant_weights": "prem=10,bulk=1",
                  "tenant_kv_budget": "bulk=0.5",
                  "enable_metrics": True})
    # a pool sized so the bulk flood saturates it: premium admission
    # then rides the fair-share queue, not spare capacity
    engine = LLMEngine(model, block_size=4, pool_blocks=24)
    admitted = obs.counter("llm_tenant_admitted_total")
    adm_before = {t: admitted.value(tenant=t) for t in ("prem", "bulk")}
    rng = np.random.default_rng(1)
    vocab = model.config.vocab_size
    results = {}
    lock = threading.Lock()

    def run_client(key, tenant, cls, prompt_len):
        prompt = rng.integers(0, vocab, size=prompt_len).astype(np.int32)
        with Client(port=srv.port, timeout_s=300.0,
                    deadline_s=300.0) as cli:
            t0 = time.perf_counter()
            toks, ttft, rejects = [], None, 0
            while ttft is None:
                try:
                    for chunk in cli.generate_stream(
                            prompt, max_new_tokens=max_new_tokens,
                            tenant=tenant, priority_class=cls):
                        if ttft is None:
                            ttft = (time.perf_counter() - t0) * 1e3
                        toks.append(int(chunk[0]))
                except RuntimeError:
                    # over the tenant KV budget: honor the backoff
                    # hint and retry — TTFT keeps counting, so the
                    # queueing a budget imposes shows in the summary
                    rejects += 1
                    time.sleep(0.05)
            with lock:
                results[key] = {"tokens": toks, "ttft_ms": ttft,
                                "rejects": rejects}

    try:
        with Server(None, llm_engine=engine) as srv:
            # warm both batch compositions once so the per-class TTFT
            # numbers below measure queueing, not XLA compilation
            with Client(port=srv.port, timeout_s=300.0) as cli:
                cli.generate(np.arange(4, dtype=np.int32),
                             max_new_tokens=2, tenant="prem",
                             priority_class="premium")
            bulk = [threading.Thread(
                        target=run_client,
                        args=(("bulk", i), "bulk", "bulk", 12))
                    for i in range(n_bulk)]
            for t in bulk:
                t.start()
            time.sleep(0.3)  # let the flood occupy the pool first
            prem = [threading.Thread(
                        target=run_client,
                        args=(("prem", i), "prem", "premium", 4))
                    for i in range(n_prem)]
            for t in prem:
                t.start()
            for t in bulk + prem:
                t.join(timeout=300)
    finally:
        pt.set_flags({"tenant_fair_share": False, "tenant_weights": "",
                      "tenant_kv_budget": "",
                      "enable_metrics": metrics_were_on})

    assert len(results) == n_bulk + n_prem, sorted(results)
    assert all(len(r["tokens"]) == max_new_tokens
               for r in results.values()), results
    assert engine.allocator.num_used == 0    # every block returned
    engine.allocator.check()

    def _cls_ttfts(kind):
        return [r["ttft_ms"] for k, r in results.items()
                if k[0] == kind]

    prem_ttft, bulk_ttft = _cls_ttfts("prem"), _cls_ttfts("bulk")
    summary = {
        "ok": True,
        "premium_clients": n_prem,
        "bulk_clients": n_bulk,
        "premium_ttft_p50_ms": _percentile(prem_ttft, 50),
        "premium_ttft_p99_ms": _percentile(prem_ttft, 99),
        "bulk_ttft_p50_ms": _percentile(bulk_ttft, 50),
        "bulk_ttft_p99_ms": _percentile(bulk_ttft, 99),
        "admitted_prem": admitted.value(tenant="prem")
        - adm_before["prem"],
        "admitted_bulk": admitted.value(tenant="bulk")
        - adm_before["bulk"],
        "bulk_rejects": sum(r["rejects"] for k, r in results.items()
                            if k[0] == "bulk"),
        "premium_rejects": sum(r["rejects"] for k, r in results.items()
                               if k[0] == "prem"),
        "preemptions": engine.scheduler.preemptions_total,
    }
    if verbose:
        print(f"llm_serving [tenants]: {n_bulk} bulk + {n_prem} "
              f"premium streams on one engine, fair share "
              f"prem=10:bulk=1, bulk KV budget 50%")
        print(f"  premium TTFT p50={summary['premium_ttft_p50_ms']:.1f}ms "
              f"p99={summary['premium_ttft_p99_ms']:.1f}ms | "
              f"bulk TTFT p50={summary['bulk_ttft_p50_ms']:.1f}ms "
              f"p99={summary['bulk_ttft_p99_ms']:.1f}ms")
        print(f"  every stream finished ({max_new_tokens} tokens "
              f"each) — fair share reorders, never starves; bulk "
              f"budget rejections={summary['bulk_rejects']} "
              f"(premium: {summary['premium_rejects']}); KV pool "
              f"clean, preemptions={summary['preemptions']}")
    return summary


if __name__ == "__main__":
    main(speculative="--speculative" in sys.argv[1:],
         router="--router" in sys.argv[1:],
         tenants="--tenants" in sys.argv[1:])
