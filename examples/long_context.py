"""Long-context attention: ring (context) parallelism over a mesh.

A sequence too long for one chip's attention memory is sharded over the
``sp`` mesh axis; each rank holds [B, H, T/n, D] of q/k/v, K/V shards
rotate around the ring (`lax.ppermute` over ICI), and per-hop partial
results merge exactly through their logsumexp weights. With
``use_flash=True`` each hop runs the Pallas flash kernel, so on-rank
attention memory is O(T/n) — not O((T/n)^2) — end to end, backward
included (ref capability: the reference scales sequence length with
fused attention kernels + model parallelism; SURVEY §5 long-context).

Runs anywhere: real chips use the Mosaic kernel; on CPU set
  XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu
and pass --interpret for the Pallas interpreter (what the smoke test
does).
"""

from __future__ import annotations

import numpy as np


def main(seq: int = 1024, verbose: bool = True,
         interpret: bool = False):
    import jax
    import jax.numpy as jnp

    import paddle_tpu as pt  # noqa: F401 (registers flags)
    from paddle_tpu.ops.attention import scaled_dot_product_attention
    from paddle_tpu.parallel import create_mesh, ring_attention

    n = len(jax.devices())
    mesh = create_mesh({"sp": n})
    b, h, d = 2, 4, 64
    rng = np.random.default_rng(0)
    q = jnp.asarray(rng.normal(0, 1, (b, h, seq, d)), jnp.float32)
    k = jnp.asarray(rng.normal(0, 1, (b, h, seq, d)), jnp.float32)
    v = jnp.asarray(rng.normal(0, 1, (b, h, seq, d)), jnp.float32)

    # context-parallel causal attention, flash kernel per ring hop
    out = ring_attention(q, k, v, mesh, causal=True, use_flash=True,
                         interpret=interpret)

    # single-device reference on the same full tensors
    ref = scaled_dot_product_attention(q, k, v, causal=True)
    err = float(jnp.max(jnp.abs(out.astype(jnp.float32)
                                - ref.astype(jnp.float32))))
    if verbose:
        print(f"ring attention over sp={n}: seq {seq} sharded to "
              f"{seq // n}/rank, max |ring - single| = {err:.2e}")

    # gradients flow through the ring (ppermute transpose + per-hop
    # flash vjp + differentiable lse merge)
    def loss(q_):
        return jnp.sum(ring_attention(q_, k, v, mesh, causal=True,
                                      use_flash=True,
                                      interpret=interpret) ** 2)

    g = jax.grad(loss)(q)
    if verbose:
        print(f"grad through the ring: |dq| = "
              f"{float(jnp.linalg.norm(g)):.3f}")
    return err


if __name__ == "__main__":
    import sys
    main(interpret="--interpret" in sys.argv)
