"""Inference: export a trained net, serve it over the native C++
transport, query it from a client (ref: the reference's
save_inference_model -> AnalysisPredictor -> serving flow).
"""

from __future__ import annotations

import os
import tempfile

import numpy as np


def main(verbose: bool = True):
    import paddle_tpu as pt
    from paddle_tpu import inference, jit
    from paddle_tpu.jit import InputSpec

    pt.seed(0)
    net = pt.nn.Sequential(pt.nn.Linear(8, 32), pt.nn.Tanh(),
                           pt.nn.Linear(32, 3))
    net.eval()
    x = np.random.default_rng(0).normal(0, 1, (4, 8)).astype(np.float32)
    want = np.asarray(net(x))

    with tempfile.TemporaryDirectory() as d:
        path = os.path.join(d, "model")
        jit.save(net, path,
                 input_spec=[InputSpec([None, 8], "float32", name="x")])

        # in-process predictor (shape-bucketed XLA executables)
        pred = inference.create_predictor(inference.Config(path))
        inp = pred.get_input_handle(pred.get_input_names()[0])
        inp.copy_from_cpu(x)
        pred.run()
        out = pred.get_output_handle(pred.get_output_names()[0]) \
            .copy_to_cpu()
        np.testing.assert_allclose(out, want, rtol=1e-5, atol=1e-5)

        # native serving transport + client over localhost
        with inference.Server(pred, max_batch=8, wait_ms=10) as srv:
            with inference.Client(port=srv.port) as cli:
                served = cli.infer([x])[0]
                # STATS control frame: live queue/served counters
                # (docs/serving_protocol.md)
                stats = cli.stats()
        np.testing.assert_allclose(served, want, rtol=1e-5, atol=1e-5)
        assert stats["replied_total"] >= 1, stats
    if verbose:
        print("inference_serving: export -> predictor -> native server "
              "round trip OK (C clients: csrc/serving_client.c); "
              f"server stats: accepted={stats['accepted_total']} "
              f"replied={stats['replied_total']} "
              f"uptime_ms={stats['uptime_ms']}")
    return {"ok": True}


if __name__ == "__main__":
    main()
