"""Render the goodput ledger from an exported metrics.json.

Usage:
    python tools/goodput_report.py [TRACE_DIR | metrics.json]
                                   [--self-test]

TRACE_DIR (default: FLAGS_trace_dir or /tmp/pt_trace) is what
``paddle_tpu.observability.export_all()`` / ``hapi.Model.fit`` with
FLAGS_trace_dir wrote; its ``metrics.json`` carries a ``goodput``
section (the ledger snapshot: exclusive per-bucket wall seconds) plus
the registry series (``badput_seconds_total{bucket=…}``,
``straggler_events_total{host=…}``). This CLI prints the operator view:
a per-bucket table, the goodput headline, and any straggler/anomaly
counts — "what fraction of wall-clock trained the model, and where did
the rest go".

``--self-test`` is the no-TPU CI hook: it runs a short CPU fit with
metrics on, asserts the ledger's invariants (buckets exclusive and
summing to wall time within 2%), then re-runs a fit in a SUBPROCESS
that SIGTERMs itself mid-flight and asserts the crash flight recorder
left a parseable ``flight_*.jsonl`` with at least 50 events.
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import subprocess
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)

_BUCKET_ORDER = ("step_compute", "jit_compile_cold",
                 "jit_compile_cache_hit", "data_wait", "eval",
                 "checkpoint", "restart_idle", "other")


def _counter_series(metrics: dict, name: str) -> dict:
    out = {}
    for s in metrics.get(name, {}).get("series", []):
        labels = s.get("labels", {})
        key = ",".join(f"{k}={v}" for k, v in sorted(labels.items()))
        out[key] = s.get("value", 0)
    return out


def render(snap: dict) -> str:
    """Format one exported snapshot (the metrics.json dict)."""
    goodput = snap.get("goodput")
    lines = []
    if not goodput:
        return ("no goodput section in metrics.json — run the fit with "
                "FLAGS_enable_metrics=1 (ledger accounting rides the "
                "metrics switch)")
    wall = goodput.get("wall_seconds", 0.0)
    buckets = goodput.get("buckets", {})
    ratios = goodput.get("ratios", {})
    lines.append("== goodput ledger ==")
    lines.append(f"{'bucket':<16} {'seconds':>12} {'share':>8}")
    for b in _BUCKET_ORDER:
        if b in buckets:
            lines.append(f"{b:<16} {buckets[b]:>12.3f} "
                         f"{100 * ratios.get(b, 0):>7.1f}%")
    for b in sorted(set(buckets) - set(_BUCKET_ORDER)):
        lines.append(f"{b:<16} {buckets[b]:>12.3f} "
                     f"{100 * ratios.get(b, 0):>7.1f}%")
    lines.append(f"{'wall':<16} {wall:>12.3f} {100.0:>7.1f}%")
    lines.append(f"goodput_ratio    {goodput.get('goodput_ratio', 0):.4f}")

    metrics = snap.get("metrics", {})
    stragglers = _counter_series(metrics, "straggler_events_total")
    if stragglers:
        lines.append("\n== straggler events ==")
        for host, n in sorted(stragglers.items()):
            lines.append(f"  {host:<20} {int(n)}")
    anomalies = _counter_series(metrics, "anomalies_total")
    if anomalies:
        lines.append("\n== anomalies ==")
        for key, n in sorted(anomalies.items()):
            lines.append(f"  {key:<32} {int(n)}")
    restarts = _counter_series(metrics, "elastic_restarts_total")
    if restarts:
        lines.append("\n== elastic restarts ==")
        for key, n in restarts.items():
            lines.append(f"  {key or 'total':<20} {int(n)}")
    return "\n".join(lines)


def report(path: str) -> int:
    mpath = path
    if os.path.isdir(path):
        mpath = os.path.join(path, "metrics.json")
    if not os.path.exists(mpath):
        print(f"no metrics.json at {mpath} — run with "
              "FLAGS_enable_metrics=1 and FLAGS_trace_dir set",
              file=sys.stderr)
        return 1
    with open(mpath) as f:
        snap = json.load(f)
    print(render(snap))
    return 0


# ------------------------------------------------------------------ CI

def _run_fit(trace_dir: str, steps: int = 64):
    """Tiny CPU fit that exercises every ledger bucket: train steps,
    an eval pass, and a checkpoint save."""
    import numpy as np

    import paddle_tpu as pt

    pt.set_flags({"enable_metrics": True, "trace_dir": trace_dir})

    class MLP(pt.nn.Layer):
        def __init__(self):
            super().__init__()
            self.fc1 = pt.nn.Linear(8, 16)
            self.fc2 = pt.nn.Linear(16, 4)

        def forward(self, x):
            return self.fc2(pt.nn.functional.relu(self.fc1(x)))

    rng = np.random.default_rng(0)
    n = steps * 4
    x = rng.normal(size=(n, 8)).astype(np.float32)
    y = rng.integers(0, 4, n).astype(np.int64)
    loader = pt.data.DataLoader(pt.data.TensorDataset(x, y),
                                batch_size=4)
    m = pt.hapi.Model(MLP())
    m.prepare(optimizer=pt.optimizer.SGD(learning_rate=1e-2),
              loss=pt.nn.CrossEntropyLoss())
    # the mid-fit ModelCheckpoint callback exercises the ledger's
    # checkpoint bucket (a save outside fit is not fit wall time)
    ckpt = pt.hapi.ModelCheckpoint(
        m, os.path.join(trace_dir, "selftest_ckpt"), save_freq=1)
    m.fit(loader, eval_loader=loader, epochs=1, verbose=0,
          callbacks=[ckpt])
    return m


def _sigterm_child(trace_dir: str) -> int:
    """Run a short fit, then deliver SIGTERM to ourselves — the flight
    recorder's handler must dump before the default action kills us."""
    _run_fit(trace_dir, steps=64)
    os.kill(os.getpid(), signal.SIGTERM)
    return 7  # unreachable: the re-raised SIGTERM terminates us


def self_test() -> int:
    import tempfile

    with tempfile.TemporaryDirectory() as d:
        _run_fit(d)
        from paddle_tpu import observability as obs
        obs.export_all(d)
        rc = report(d)
        if rc != 0:
            return rc
        with open(os.path.join(d, "metrics.json")) as f:
            snap = json.load(f)
        gp = snap["goodput"]
        wall, buckets = gp["wall_seconds"], gp["buckets"]
        # exclusivity: buckets are disjoint classifications of wall
        # time, so they must sum back to it (±2%) and each be sane
        total = sum(buckets.values())
        assert wall > 0 and abs(total - wall) <= 0.02 * wall, \
            (wall, buckets)
        assert all(v >= 0 for v in buckets.values()), buckets
        assert abs(sum(gp["ratios"].values()) - 1.0) <= 0.02
        assert buckets["step_compute"] > 0 and buckets["eval"] > 0
        assert buckets["checkpoint"] > 0 \
            and buckets["jit_compile_cold"] > 0
        assert gp["goodput_ratio"] == \
            buckets["step_compute"] / max(wall, 1e-12)

    # crash path: a separate interpreter SIGTERMs itself mid-run
    with tempfile.TemporaryDirectory() as d:
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__),
             "--sigterm-child", d],
            capture_output=True, text=True, env=env, timeout=480)
        assert proc.returncode != 0, "child survived its own SIGTERM"
        flights = [f for f in os.listdir(d) if f.startswith("flight_")]
        assert flights, (proc.stdout, proc.stderr)
        with open(os.path.join(d, sorted(flights)[-1])) as f:
            lines = [json.loads(line) for line in f]
        assert lines[0]["kind"] == "flight_header"
        assert lines[0]["reason"].startswith("signal:")
        assert lines[-1]["kind"] == "final_metrics"
        events = lines[1:-1]
        assert len(events) >= 50, len(events)
        kinds = {e["kind"] for e in events}
        assert "step" in kinds and "signal" in kinds, kinds
    print("\nself-test OK")
    return 0


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("path", nargs="?", default="")
    ap.add_argument("--self-test", action="store_true")
    ap.add_argument("--sigterm-child", metavar="DIR", default="",
                    help=argparse.SUPPRESS)  # internal: self-test crash half
    args = ap.parse_args()
    if args.sigterm_child:
        return _sigterm_child(args.sigterm_child)
    if args.self_test:
        return self_test()
    path = args.path
    if not path:
        from paddle_tpu.flags import GLOBAL_FLAGS
        path = GLOBAL_FLAGS.get("trace_dir") or "/tmp/pt_trace"
    return report(path)


if __name__ == "__main__":
    sys.exit(main())
