"""Watch for the accelerator tunnel to come back, then run the capture
campaign (tools/capture_all.py) for whichever stages still lack a good
artifact, looping until every wanted stage has one.

Each probe runs ``jax.default_backend()`` in a subprocess with a hard
timeout so a wedged PJRT init never hangs the watcher. Probe cadence is
~3 min; every outcome is appended to tools/tunnel_watch.log with a
timestamp so the outage window is documented for the round ledger.

The round-3 tunnel flaps (up for minutes, down for hours), so a single
campaign run is not enough: after each attempt the watcher re-reads the
CAPTURE_*.json artifacts and retries only the stages that are still
missing or not ok.

Usage: python tools/tunnel_watch.py [stage ...]
Stages are forwarded to capture_all.py (default: its DEFAULT_PLAN).
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
LOG = os.path.join(ROOT, "tools", "tunnel_watch.log")
sys.path.insert(0, os.path.join(ROOT, "tools"))
from capture_all import DEFAULT_PLAN, STAGES, resolve_plan  # noqa: E402

# Deliberately NOT imported from paddle_tpu.core.place (the canonical
# copy): the watcher's whole design is that jax/PJRT/framework code
# runs only inside hard-timeout subprocesses, so a broken framework
# import can never wedge the watcher itself. Keep in sync with
# paddle_tpu.core.place.ACCEL_PLATFORMS.
ACCEL_PLATFORMS = ("tpu", "axon")

# a stage that fails deterministically (e.g. a pinned batch that OOMs)
# must not burn its full chip-time budget forever — give up after this
# many campaign attempts that included it
MAX_ATTEMPTS_PER_STAGE = 4


def log(msg: str) -> None:
    line = f"{time.strftime('%Y-%m-%dT%H:%M:%SZ', time.gmtime())} {msg}"
    print(line, file=sys.stderr, flush=True)
    with open(LOG, "a") as f:
        f.write(line + "\n")


def probe(timeout_s: int = 60) -> str | None:
    try:
        r = subprocess.run(
            [sys.executable, "-c",
             "import jax; print(jax.default_backend())"],
            capture_output=True, timeout=timeout_s, text=True)
    except subprocess.TimeoutExpired:
        return None
    if r.returncode == 0 and r.stdout.strip():
        return r.stdout.strip().splitlines()[-1]
    return None


def missing_stages(wanted: list[str]) -> list[str]:
    out = []
    for name in wanted:
        path = os.path.join(ROOT, f"CAPTURE_{name}.json")
        try:
            with open(path) as f:
                if json.load(f).get("ok"):
                    continue
        except (OSError, json.JSONDecodeError):
            pass
        out.append(name)
    return out


def _stage_ran(name: str) -> bool:
    """True when the stage's artifact shows it actually got chip time —
    only those runs count against MAX_ATTEMPTS_PER_STAGE. A stage that
    aborted on its backend probe (rc=3) never ran: the tunnel dropped
    between the watcher's probe and the stage's turn in the campaign, so
    a flapping tunnel can't permanently abandon stages it starved.
    Timeouts DO count: a mid-run tunnel drop can look like one, but only
    for the single stage that was executing (later stages fail rc=3), so
    a deterministically-hanging stage still exhausts its attempts
    instead of burning its budget forever."""
    try:
        with open(os.path.join(ROOT, f"CAPTURE_{name}.json")) as f:
            d = json.load(f)
    except (OSError, json.JSONDecodeError):
        return False
    return d.get("rc") != 3


def main() -> None:
    wanted = resolve_plan(sys.argv[1:] or list(DEFAULT_PLAN))
    unknown = [w for w in wanted if w not in STAGES]
    if unknown:
        raise SystemExit(f"unknown stages {unknown}; pick from "
                         f"{sorted(STAGES)}")
    log(f"watch start (stages={wanted})")
    n = 0
    attempts: dict[str, int] = {}
    while True:
        todo = [s for s in missing_stages(wanted)
                if attempts.get(s, 0) < MAX_ATTEMPTS_PER_STAGE]
        if not todo:
            done = [s for s in wanted
                    if s not in missing_stages(wanted)]
            log(f"nothing left to try (good artifacts: {done}; "
                f"given up: {sorted(set(wanted) - set(done))}); exiting")
            sys.exit(0 if len(done) == len(wanted) else 1)
        backend = probe()
        if backend in ACCEL_PLATFORMS:
            log(f"probe {n}: backend={backend} — tunnel UP; "
                f"capturing {todo}")
            r = subprocess.run(
                [sys.executable,
                 os.path.join(ROOT, "tools", "capture_all.py"), *todo],
                cwd=ROOT)
            for s in todo:
                if _stage_ran(s):
                    attempts[s] = attempts.get(s, 0) + 1
            log(f"capture campaign rc={r.returncode}")
            time.sleep(60)  # don't spin if a stage fails for a
            continue        # non-tunnel reason; re-check artifacts
        log(f"probe {n}: {'backend=' + backend if backend else 'down'}")
        n += 1
        time.sleep(180)


if __name__ == "__main__":
    main()
