"""Watch for the accelerator tunnel to come back, then run the capture
campaign (tools/capture_all.py) once and exit.

Each probe runs ``jax.default_backend()`` in a subprocess with a hard
timeout so a wedged PJRT init never hangs the watcher. Probe cadence is
~3 min; every outcome is appended to tools/tunnel_watch.log with a
timestamp so the outage window is documented for the round ledger.

Usage: python tools/tunnel_watch.py [stage ...]
Stages are forwarded to capture_all.py (default: the full campaign).
"""

from __future__ import annotations

import os
import subprocess
import sys
import time

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
LOG = os.path.join(ROOT, "tools", "tunnel_watch.log")


def log(msg: str) -> None:
    line = f"{time.strftime('%Y-%m-%dT%H:%M:%SZ', time.gmtime())} {msg}"
    print(line, file=sys.stderr, flush=True)
    with open(LOG, "a") as f:
        f.write(line + "\n")


def probe(timeout_s: int = 60) -> str | None:
    try:
        r = subprocess.run(
            [sys.executable, "-c",
             "import jax; print(jax.default_backend())"],
            capture_output=True, timeout=timeout_s, text=True)
    except subprocess.TimeoutExpired:
        return None
    if r.returncode == 0 and r.stdout.strip():
        return r.stdout.strip().splitlines()[-1]
    return None


def main() -> None:
    stages = sys.argv[1:]
    log(f"watch start (stages={stages or 'all'})")
    n = 0
    while True:
        backend = probe()
        if backend in ("tpu", "axon"):
            log(f"probe {n}: backend={backend} — tunnel UP; "
                f"starting capture campaign")
            r = subprocess.run(
                [sys.executable,
                 os.path.join(ROOT, "tools", "capture_all.py"), *stages],
                cwd=ROOT)
            log(f"capture campaign rc={r.returncode}")
            sys.exit(r.returncode)
        log(f"probe {n}: {'backend=' + backend if backend else 'down'}")
        n += 1
        time.sleep(150)


if __name__ == "__main__":
    main()
