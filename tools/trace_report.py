"""Merge a host chrome-trace with an optional XLA device trace and
print the reference-style aggregated span summary.

Usage:
    python tools/trace_report.py [TRACE_DIR] [--xla DIR_OR_GLOB]
                                 [--top K] [--self-test]

TRACE_DIR (default: FLAGS_trace_dir or /tmp/pt_trace) is what
``paddle_tpu.observability.export_all()`` / ``hapi.Model.fit`` with
FLAGS_trace_dir wrote: ``host_trace.json`` (chrome traceEvents) and
``metrics.json`` (metrics + recompile snapshot). With ``--xla`` (or
when XLA ``*.trace.json.gz`` files sit under TRACE_DIR, e.g. a
jax.profiler capture into the same directory), device op events join
the same table prefixed ``xla::`` and the device-op category rollup is
printed too.

``--self-test`` exercises the whole path without a TPU (or any
accelerator work): synthesizes spans, exports, re-parses, prints the
table, exits 0 — the CI hook for this tool.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)

from paddle_tpu.observability import trace_agg  # noqa: E402


def _load_host_events(trace_dir: str):
    path = os.path.join(trace_dir, "host_trace.json")
    if os.path.isfile(trace_dir) and trace_dir.endswith(".json"):
        path = trace_dir
    if not os.path.exists(path):
        return None, path
    return trace_agg.load_trace_events(path), path


def _print_metrics_snapshot(trace_dir: str) -> None:
    mpath = os.path.join(trace_dir, "metrics.json")
    if not os.path.exists(mpath):
        return
    with open(mpath) as f:
        snap = json.load(f)
    metrics = snap.get("metrics", {})
    if metrics:
        print("\n== metrics snapshot ==")
        for name in sorted(metrics):
            m = metrics[name]
            for s in m.get("series", []):
                labels = ",".join(f"{k}={v}" for k, v in
                                  sorted(s.get("labels", {}).items()))
                tag = f"{name}{{{labels}}}" if labels else name
                if m.get("type") == "histogram":
                    cnt, tot = s.get("count", 0), s.get("sum", 0.0)
                    avg = tot / cnt if cnt else 0.0
                    print(f"  {tag:<52} count={cnt} sum={tot:.6g} "
                          f"avg={avg:.6g}")
                else:
                    print(f"  {tag:<52} {s.get('value')}")
    recomp = snap.get("recompile", {})
    if recomp:
        print("\n== jit recompile report ==")
        for name in sorted(recomp):
            r = recomp[name]
            n_sig = len(r.get("signatures", []))
            comp = sum(r.get("compile_times_s", []))
            print(f"  {name:<48} traces={r['traces']} "
                  f"hits={r['hits']} shapes={n_sig} "
                  f"compile_s={comp:.3f}")
    programs = snap.get("programs", {})
    if programs:
        print("\n== compiled-program cards ==")
        for name in sorted(programs):
            for sig, card in programs[name].items():
                if card.get("unavailable"):
                    print(f"  {name:<40} {sig[:40]:<42} "
                          f"unavailable: {card['unavailable']}")
                    continue
                flops = card.get("flops", 0.0)
                peak = card.get("peak_bytes_estimate", 0)
                print(f"  {name:<40} {sig[:40]:<42} "
                      f"flops={flops:.4g} "
                      f"bytes={card.get('bytes_accessed', 0):.4g} "
                      f"peak_mem={peak / 1e6:.3f}MB")
    native = snap.get("native_stats", {})
    if native:
        print("\n== native stats (pt_mon) ==")
        for k in sorted(native):
            print(f"  {k:<52} {native[k]}")


def report(trace_dir: str, xla: str = "", top: int = 30) -> int:
    host_events, host_path = _load_host_events(trace_dir)
    summary = {}
    if host_events is None:
        print(f"note: no host trace at {host_path}", file=sys.stderr)
    else:
        summary.update(trace_agg.span_summary(host_events))

    # device side: explicit --xla dir/file, else any capture under
    # trace_dir
    xla_paths = []
    if xla:
        xla_paths = [xla] if os.path.isfile(xla) \
            else trace_agg.find_xla_traces(xla)
    elif os.path.isdir(trace_dir):
        xla_paths = trace_agg.find_xla_traces(trace_dir)
    if xla_paths:
        xla_events = trace_agg.load_trace_events(xla_paths[-1])
        try:
            rollup = trace_agg.xla_op_rollup(xla_events)
            print(trace_agg.format_xla_rollup(rollup, top=top))
            print()
            for name, op in rollup["ops"].items():
                summary["xla::" + name] = {
                    "calls": op["count"], "total_us": op["dur_us"],
                    "max_us": 0.0,
                    "avg_us": op["dur_us"] / max(op["count"], 1)}
        except trace_agg.TraceFormatError as e:
            print(f"warning: {e}", file=sys.stderr)

    if not summary:
        print("no spans found — run with FLAGS_enable_metrics=1 and "
              "FLAGS_trace_dir set (or pass a trace directory)",
              file=sys.stderr)
        return 1
    print(trace_agg.format_span_table(summary, top=top,
                                      title="merged span summary"))
    _print_metrics_snapshot(trace_dir)
    return 0


def self_test() -> int:
    """No-TPU smoke: synthesize spans + metrics, export, re-report."""
    import tempfile
    import time

    from paddle_tpu import observability as obs

    with tempfile.TemporaryDirectory() as d:
        tr = obs.get_tracer()
        for i in range(3):
            with tr.span("selftest/step", force=True):
                with tr.span("selftest/inner", force=True):
                    time.sleep(0.001)
        obs.counter("selftest_total", always=True).inc(3)
        obs.export_all(d)
        rc = report(d)
        if rc != 0:
            return rc
        summary = trace_agg.span_summary(
            trace_agg.load_trace_events(
                os.path.join(d, "host_trace.json")))
        assert summary["selftest/step"]["calls"] == 3, summary
        assert summary["selftest/inner"]["total_us"] > 0, summary
    print("\nself-test OK")
    return 0


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("trace_dir", nargs="?", default="")
    ap.add_argument("--xla", default="",
                    help="XLA profiler dir or *.trace.json.gz file")
    ap.add_argument("--top", type=int, default=30)
    ap.add_argument("--self-test", action="store_true")
    args = ap.parse_args()
    if args.self_test:
        return self_test()
    trace_dir = args.trace_dir
    if not trace_dir:
        from paddle_tpu.flags import GLOBAL_FLAGS
        trace_dir = GLOBAL_FLAGS.get("trace_dir") or "/tmp/pt_trace"
    return report(trace_dir, xla=args.xla, top=args.top)


if __name__ == "__main__":
    sys.exit(main())
