"""Serving flight deck: p99 latency attribution for the LLM engine.

Usage:
    python tools/serving_report.py [--url http://host:port | --input F]
                                   [--pct 99] [--threshold-ms MS]
                                   [--top N] [--json] [--chrome OUT]
                                   [--self-test]

Joins the per-sequence lifecycle timelines (/llm/seqs,
observability/seqtrace.py) against the engine step records
(/llm/steps, observability/stepprof.py) and answers the operator
question behind every tail-latency page: *which inter-token gaps blew
past the p99, and what was the engine doing instead of decoding?*

For every gap above the threshold (an explicit --threshold-ms, else
the --pct percentile of all observed gaps) the report names the
dominant cause and splits the gap into EXCLUSIVE buckets that sum to
the gap — the goodput-ledger discipline applied to a single token gap.
Buckets, in charge order (each clipped to the budget the earlier ones
left):

- ``preempt_recompute`` — the sequence was preempted inside the gap:
  from the preemption stamp to the end of its recompute prefill.
- ``spec_rollback``     — speculative windows in the gap that rolled
  draft tokens back (propose + verify time of rejected work).
- ``cow_copy``          — copy-on-write block privatization inside
  the gap (shared-prefix divergence).
- ``chunk_interleave``  — engine prefill time spent on OTHER
  sequences' chunks interleaved into this gap (step prefill phase
  time overlapping the gap, minus this sequence's own chunks).
- ``stall``             — overlap with steps the stall watchdog
  flagged (llm_engine_stalled).
- ``queue``             — waiting for (re)admission at the head of
  the gap.
- ``other``             — the unexplained remainder (normal decode
  compute lands here).

``--chrome OUT`` additionally exports the joined view as a Chrome
``traceEvents`` JSON (Perfetto-loadable): one track per engine phase
under an "llm engine steps" process and one track per sequence under
"llm sequences", so the same data reads as a timeline.

Input comes from the in-process rings (after driving an engine in the
same interpreter), an ``--input`` JSON file (endpoint dumps: either
``{"seqs": <//llm/seqs>, "steps": <//llm/steps>}`` or the two payload
shapes directly), or a live exporter via ``--url``.

``--self-test`` is the no-TPU CI hook: it engineers one scenario per
cause on a real CPU engine — preemption under pool pressure, chunked
prefill interleaving, speculative rollback with a divergent draft,
COW divergence on a shared prefix, a watchdog-flagged stall (via
``testing.faults`` ``sleep=`` latency injections) — and asserts the
report pins each engineered gap on the intended cause, that buckets
are exclusive and sum to the gap within 5%, and that a 200-stream
flood keeps both rings bounded with zero KV leak.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Any, Dict, List, Optional, Tuple

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)

CAUSES = ("preempt_recompute", "spec_rollback", "cow_copy",
          "chunk_interleave", "stall", "queue", "other")

# top-level step phases laid out sequentially on the chrome timeline;
# sample/scatter are overlapping sub-segments anchored at step begin
_PHASE_ORDER = ("admit", "prefill", "decode", "spec_verify")
_SUB_PHASES = ("sample", "scatter")


# ------------------------------------------------------------------ load

def load_rings() -> Tuple[List[dict], List[dict]]:
    """Timelines (live + finished) and step records from the
    in-process rings."""
    from paddle_tpu.observability import seqtrace, stepprof
    sr = seqtrace.ring()
    return sr.live() + sr.recent(), stepprof.ring().recent()


def _split_payloads(seqs: dict, steps: dict
                    ) -> Tuple[List[dict], List[dict]]:
    timelines = list(seqs.get("live") or []) \
        + list(seqs.get("finished") or []) \
        + list(seqs.get("timelines") or [])
    return timelines, list(steps.get("steps") or [])


def load_file(path: str) -> Tuple[List[dict], List[dict]]:
    with open(path) as f:
        blob = json.load(f)
    return _split_payloads(blob.get("seqs", blob),
                           blob.get("steps", blob))


def load_url(url: str) -> Tuple[List[dict], List[dict]]:
    import urllib.request

    def fetch(path):
        with urllib.request.urlopen(url.rstrip("/") + path,
                                    timeout=10) as r:
            return json.loads(r.read().decode())

    return _split_payloads(fetch("/llm/seqs"), fetch("/llm/steps"))


# -------------------------------------------------------------- analysis

def _percentile(vals: List[float], pct: float) -> float:
    # the one shared estimator (observability/metrics.py) so this
    # report's percentiles agree with the SLO engine's
    if not vals:
        return 0.0
    from paddle_tpu.observability import metrics as _m
    return _m.percentile(vals, pct)


def gaps_of(tl: dict) -> List[dict]:
    """Inter-token gaps of one timeline: begin -> first token (the
    TTFT gap), then each consecutive token pair."""
    anchors: List[Tuple[Any, float]] = [("begin", tl["begin_mono"])]
    for e in tl.get("events", []):
        if e.get("ev") == "token":
            anchors.append((e.get("index"), e["t_mono"]))
    out = []
    for i in range(1, len(anchors)):
        a, b = anchors[i - 1][1], anchors[i][1]
        out.append({"token": anchors[i][0], "a": a, "b": b,
                    "gap_ms": (b - a) * 1e3, "first": i == 1})
    return out


def _step_overlap_ms(rec: dict, a: float, b: float
                     ) -> Tuple[float, float]:
    """(overlap_ms, fraction of the step inside the window)."""
    t0 = rec.get("begin_mono")
    dur_s = float(rec.get("dur_ms") or 0.0) / 1e3
    if t0 is None or dur_s <= 0:
        return 0.0, 0.0
    ov = min(b, t0 + dur_s) - max(a, t0)
    if ov <= 0:
        return 0.0, 0.0
    return ov * 1e3, ov / dur_s


def attribute(tl: dict, gap: dict, steps: List[dict]) -> dict:
    """Split one gap into the exclusive cause buckets. Charge order is
    most-specific evidence first; each bucket is clipped to what the
    earlier ones left, so the buckets sum to the gap exactly."""
    a, b = gap["a"], gap["b"]
    evs = [e for e in tl.get("events", []) if a < e["t_mono"] <= b]
    remaining = gap["gap_ms"]
    buckets: Dict[str, float] = {}

    def take(name: str, ms: float) -> None:
        nonlocal remaining
        ms = max(0.0, min(ms, remaining))
        buckets[name] = round(ms, 3)
        remaining -= ms

    pre = [e["t_mono"] for e in evs if e["ev"] == "preempted"]
    if pre:
        # preemption to the end of the recompute prefill (or the gap
        # end if the recompute is still running / untraced)
        chunks = [e["t_mono"] for e in evs
                  if e["ev"] == "prefill_chunk" and e["t_mono"] >= pre[0]]
        take("preempt_recompute",
             ((max(chunks) if chunks else b) - pre[0]) * 1e3)
    else:
        take("preempt_recompute", 0.0)
    take("spec_rollback",
         sum(float(e.get("ms") or 0.0) for e in evs
             if e["ev"] == "spec_window" and e.get("rollback")))
    take("cow_copy", sum(float(e.get("ms") or 0.0) for e in evs
                         if e["ev"] == "cow_copy"))
    own_prefill = sum(float(e.get("ms") or 0.0) for e in evs
                      if e["ev"] == "prefill_chunk")
    steal = 0.0
    stall = 0.0
    for rec in steps:
        ov_ms, frac = _step_overlap_ms(rec, a, b)
        if not ov_ms:
            continue
        steal += frac * float(
            (rec.get("phase_ms") or {}).get("prefill") or 0.0)
        if rec.get("stalled"):
            stall += ov_ms
    take("chunk_interleave", steal - own_prefill)
    take("stall", stall)
    adm = [e["t_mono"] for e in evs
           if e["ev"] in ("admitted", "readmitted")]
    take("queue", (adm[0] - a) * 1e3 if adm else 0.0)
    buckets["other"] = round(remaining, 3)
    # insertion order is charge order, so a tie resolves to the more
    # specific cause (max returns the first maximal key)
    cause = max(buckets, key=lambda k: buckets[k])
    return {"cause": cause, "buckets": buckets}


def analyze(timelines: List[dict], steps: List[dict],
            threshold_ms: Optional[float] = None,
            pct: float = 99.0) -> dict:
    """The report payload: every gap at/above the threshold,
    attributed. ``threshold_ms`` overrides the percentile."""
    pairs = [(tl, g) for tl in timelines for g in gaps_of(tl)]
    vals = [g["gap_ms"] for _, g in pairs]
    thr = float(threshold_ms) if threshold_ms is not None \
        else _percentile(vals, pct)
    findings = []
    for tl, g in pairs:
        if g["gap_ms"] < thr or g["gap_ms"] <= 0:
            continue
        att = attribute(tl, g, steps)
        findings.append({
            "seq_id": tl.get("seq_id"), "trace_id": tl.get("trace_id"),
            "token": g["token"], "first_token": g["first"],
            "gap_ms": round(g["gap_ms"], 3),
            "cause": att["cause"], "buckets": att["buckets"]})
    findings.sort(key=lambda f: -f["gap_ms"])
    return {"threshold_ms": round(thr, 3), "pct": pct,
            "gaps_total": len(vals), "sequences": len(timelines),
            "steps": len(steps), "findings": findings}


# --------------------------------------------------------- chrome export

def chrome_trace(timelines: List[dict], steps: List[dict]) -> dict:
    """The joined flight-deck view as Chrome ``traceEvents``: engine
    phases laid out per step under one process, one track per
    sequence under another. Timestamps are the monotonic stamps the
    stores carry (µs), so both processes share one clock domain."""
    ev: List[dict] = [
        {"name": "process_name", "ph": "M", "pid": 1,
         "args": {"name": "llm engine steps"}},
        {"name": "process_name", "ph": "M", "pid": 2,
         "args": {"name": "llm sequences"}},
        {"name": "thread_name", "ph": "M", "pid": 1, "tid": 0,
         "args": {"name": "step"}}]
    for i, ph in enumerate(_PHASE_ORDER + _SUB_PHASES):
        ev.append({"name": "thread_name", "ph": "M", "pid": 1,
                   "tid": i + 1, "args": {"name": f"phase:{ph}"}})
    for rec in steps:
        t0 = rec.get("begin_mono")
        if t0 is None:
            continue
        ts = t0 * 1e6
        ev.append({"name": f"step {rec.get('step')}", "ph": "X",
                   "pid": 1, "tid": 0, "ts": ts,
                   "dur": float(rec.get("dur_ms") or 0.0) * 1e3,
                   "args": {k: rec.get(k) for k in
                            ("batch", "kv", "spec", "tokens",
                             "stalled")}})
        pm = rec.get("phase_ms") or {}
        cursor = ts
        for i, ph in enumerate(_PHASE_ORDER + _SUB_PHASES):
            ms = float(pm.get(ph) or 0.0)
            if ms <= 0:
                continue
            start = ts if ph in _SUB_PHASES else cursor
            ev.append({"name": ph, "ph": "X", "pid": 1, "tid": i + 1,
                       "ts": start, "dur": ms * 1e3})
            if ph not in _SUB_PHASES:
                cursor += ms * 1e3
    for tl in timelines:
        tid = tl.get("seq_id", 0)
        ev.append({"name": "thread_name", "ph": "M", "pid": 2,
                   "tid": tid,
                   "args": {"name": f"seq {tid} "
                                    f"(trace {tl.get('trace_id')})"}})
        for e in tl.get("events", []):
            ts = e["t_mono"] * 1e6
            args = {k: v for k, v in e.items()
                    if k not in ("ev", "t_mono")}
            ms = float(e.get("ms") or 0.0)
            if ms > 0:
                # timed events are stamped at completion; draw the
                # slice backwards from the stamp
                ev.append({"name": e["ev"], "ph": "X", "pid": 2,
                           "tid": tid, "ts": ts - ms * 1e3,
                           "dur": ms * 1e3, "args": args})
            else:
                ev.append({"name": e["ev"], "ph": "i", "pid": 2,
                           "tid": tid, "ts": ts, "s": "t",
                           "args": args})
    return {"traceEvents": ev, "displayTimeUnit": "ms"}


# --------------------------------------------------------------- render

def render(report: dict, top: int = 20) -> str:
    lines = ["== serving latency attribution =="]
    lines.append(
        f"sequences {report['sequences']}  steps {report['steps']}  "
        f"gaps {report['gaps_total']}  threshold "
        f"{report['threshold_ms']:.1f} ms (p{report['pct']:g})")
    fnd = report["findings"]
    if not fnd:
        lines.append("no gaps above threshold")
        return "\n".join(lines)
    lines.append(f"{'seq':>5} {'trace':>6} {'token':>6} "
                 f"{'gap_ms':>9}  cause")
    for f in fnd[:top]:
        lines.append(f"{f['seq_id']:>5} {f['trace_id']:>6} "
                     f"{str(f['token']):>6} {f['gap_ms']:>9.1f}  "
                     f"{f['cause']}")
        parts = [f"{k}={v:.1f}" for k, v in f["buckets"].items() if v]
        lines.append(f"{'':>30}{' '.join(parts)}")
    if len(fnd) > top:
        lines.append(f"... {len(fnd) - top} more "
                     f"(--top to widen)")
    by_cause: Dict[str, int] = {}
    for f in fnd:
        by_cause[f["cause"]] = by_cause.get(f["cause"], 0) + 1
    lines.append("by cause: " + "  ".join(
        f"{c}={by_cause[c]}" for c in CAUSES if c in by_cause))
    return "\n".join(lines)


# ------------------------------------------------------------- self-test

def _assert_ledger(report: dict) -> None:
    """Buckets non-negative, exclusive, summing to the gap ±5%."""
    for f in report["findings"]:
        s = sum(f["buckets"].values())
        assert all(v >= 0 for v in f["buckets"].values()), f
        assert abs(s - f["gap_ms"]) <= max(0.05 * f["gap_ms"], 0.5), \
            (s, f)
        assert set(f["buckets"]) == set(CAUSES), f


def _drive(eng, max_steps: int = 400) -> int:
    n = 0
    while eng.active() and n < max_steps:
        eng.step()
        n += 1
    return n


_BASE_FLAGS = {"enable_metrics": True, "fault_spec": "",
               "prefill_chunk_tokens": 0, "kv_prefix_sharing": False,
               "speculative_k": 0, "kv_admission_watermark": 0.0,
               "llm_stall_factor": 10.0}


def _fresh(**flags):
    """Reset flags + rings to a known state and return a new
    (engine factory, model) pair for one scenario."""
    import paddle_tpu as pt
    from paddle_tpu.observability import seqtrace, stepprof
    from paddle_tpu.testing import faults
    merged = dict(_BASE_FLAGS)
    merged.update(flags)
    pt.set_flags(merged)
    faults.configure(merged.get("fault_spec") or None)
    seqtrace.ring().reset()
    stepprof.ring().reset()


def _arm(spec: str) -> None:
    import paddle_tpu as pt
    pt.set_flags({"fault_spec": spec})


def _report(threshold_ms: float) -> dict:
    tls, steps = load_rings()
    rep = analyze(tls, steps, threshold_ms=threshold_ms)
    _assert_ledger(rep)
    return rep


def self_test() -> int:
    import numpy as np

    import paddle_tpu as pt
    from paddle_tpu.models.gpt_lm import GPTConfig, GPTLanguageModel
    from paddle_tpu.serving_llm import engine as engine_mod
    from paddle_tpu.serving_llm.engine import LLMEngine
    from paddle_tpu.sysconfig import enable_compile_cache

    enable_compile_cache()
    model = GPTLanguageModel(GPTConfig())

    def prompt(n, base=1):
        return np.arange(base, base + n, dtype=np.int32) % 250

    # -- 1. preemption + recompute ------------------------------------
    _fresh()
    eng = LLMEngine(model, pool_blocks=8, block_size=4)
    eng.add_request(prompt(8), max_new_tokens=20, trace_id=1)
    eng.add_request(prompt(8, base=100), max_new_tokens=16, trace_id=2)
    _arm("llm_decode:sleep=10")
    _drive(eng)
    _arm("")
    assert eng.scheduler.preemptions_total > 0, "no preemption engineered"
    rep = _report(threshold_ms=40.0)
    victims = [f for f in rep["findings"]
               if f["buckets"]["preempt_recompute"] > 0]
    assert victims, rep
    assert all(f["cause"] == "preempt_recompute" for f in victims), \
        victims
    print(f"  preempt_recompute OK ({len(victims)} gap(s))")

    # -- 2. chunked-prefill interleaving ------------------------------
    _fresh(prefill_chunk_tokens=4)
    eng = LLMEngine(model, pool_blocks=64, block_size=4)
    a = eng.add_request(prompt(4), max_new_tokens=12, trace_id=3)
    for _ in range(3):
        eng.step()  # A past prefill, decoding
    _arm("llm_chunk_prefill:sleep=120")
    eng.add_request(prompt(16, base=50), max_new_tokens=2, trace_id=4)
    for _ in range(4):
        eng.step()  # B's 4 slow chunks interleave with A's decode
    _arm("")
    _drive(eng)
    rep = _report(threshold_ms=60.0)
    # the engineered gaps: A's decode windows that absorbed one of
    # B's 120 ms chunks (cold-compile gaps attribute to "other")
    mine = [f for f in rep["findings"] if f["seq_id"] == a
            and f["buckets"]["chunk_interleave"] >= 60.0]
    assert mine, rep
    assert all(f["cause"] == "chunk_interleave" for f in mine), mine
    print(f"  chunk_interleave OK ({len(mine)} gap(s))")

    # -- 3. speculative rollback --------------------------------------
    _fresh(speculative_k=3)
    draft = GPTLanguageModel(GPTConfig(num_layers=1))
    eng = LLMEngine(model, pool_blocks=32, block_size=4,
                    draft_model=draft)
    eng.add_request(prompt(6), max_new_tokens=8, trace_id=5)
    _arm("llm_spec_verify:sleep=80")
    _drive(eng)
    _arm("")
    assert eng.spec_proposed_total > eng.spec_accepted_total, \
        "divergent draft did not roll back"
    rep = _report(threshold_ms=40.0)
    rb = [f for f in rep["findings"]
          if f["buckets"]["spec_rollback"] > 0]
    assert rb, rep
    assert all(f["cause"] == "spec_rollback" for f in rb), rb
    print(f"  spec_rollback OK ({len(rb)} gap(s))")

    # -- 4. copy-on-write divergence ----------------------------------
    _fresh(kv_prefix_sharing=True)
    eng = LLMEngine(model, pool_blocks=32, block_size=4)
    eng.add_request(prompt(10), max_new_tokens=12, trace_id=6)
    # warm B's exact graph with twins (same shared prefix, different
    # divergent tails): prefix-cached 6-token prefill + the COW copy
    # op take ~3 repetitions to fully warm on CPU, so B's TTFT gap
    # below is the engineered COW, not a cold compile
    for base in (210, 220, 230):
        eng.add_request(np.concatenate([prompt(10),
                                        prompt(6, base=base)]),
                        max_new_tokens=1, trace_id=60)
    for _ in range(4):
        eng.step()  # A resident; its prompt blocks now shareable
    # 2 s injected copy latency: large enough that the COW dominates
    # B's TTFT gap even over residual cold-trace noise on slow CI
    _arm("llm_cow_copy:sleep=2000")
    bb = eng.add_request(
        np.concatenate([prompt(10), prompt(6, base=200)]),
        max_new_tokens=2, trace_id=7)
    for _ in range(3):
        eng.step()  # B prefill: shared-tail divergence -> COW copy
    _arm("")
    _drive(eng)
    assert eng.allocator.cow_copies_total > 0, "no COW engineered"
    rep = _report(threshold_ms=500.0)
    cw = [f for f in rep["findings"]
          if f["seq_id"] == bb and f["buckets"]["cow_copy"] > 0]
    assert cw, rep
    assert all(f["cause"] == "cow_copy" for f in cw), cw
    print(f"  cow_copy OK ({len(cw)} gap(s))")

    # -- 5. watchdog stall --------------------------------------------
    _fresh(llm_stall_factor=3.0)
    stall_min = engine_mod.STALL_MIN_S
    engine_mod.STALL_MIN_S = 0.05
    try:
        eng = LLMEngine(model, pool_blocks=32, block_size=4)
        # late injection (at=15): the 0.8/0.2 EWMA needs ~a dozen
        # fast steps to forget any cold first step, else
        # factor x ewma still exceeds the injected delay
        eng.add_request(prompt(4), max_new_tokens=20, trace_id=8)
        _arm("llm_decode:at=15:sleep=700")
        _drive(eng)
        _arm("")
    finally:
        engine_mod.STALL_MIN_S = stall_min
    assert eng.stalls_total > 0, "watchdog never fired"
    rep = _report(threshold_ms=350.0)
    st = [f for f in rep["findings"] if f["buckets"]["stall"] > 0]
    assert st, rep
    assert all(f["cause"] == "stall" for f in st), st
    print(f"  stall OK ({len(st)} gap(s))")

    # chrome export of the stall scenario parses and carries both
    # processes + the timed slices
    tls, steps = load_rings()
    trace = json.loads(json.dumps(chrome_trace(tls, steps)))
    names = {e.get("args", {}).get("name") for e in trace["traceEvents"]
             if e.get("ph") == "M"}
    assert {"llm engine steps", "llm sequences"} <= names, names
    assert any(e.get("ph") == "X" and e.get("pid") == 2
               for e in trace["traceEvents"]), "no sequence slices"
    assert render(_report(threshold_ms=350.0))
    print("  chrome export OK")

    # -- 6. 200-stream flood: rings bounded, zero KV leak -------------
    _fresh(llm_seqtrace_ring=64, llm_step_ring=32)
    try:
        from paddle_tpu.observability import seqtrace, stepprof
        eng = LLMEngine(model, pool_blocks=64, block_size=4)
        for i in range(200):
            eng.add_request(prompt(4, base=i % 200), max_new_tokens=2,
                            trace_id=1000 + i)
        _drive(eng, max_steps=1000)
        assert not eng.active(), "flood did not drain"
        assert len(seqtrace.ring().recent()) <= 64
        assert seqtrace.ring().live() == []
        assert len(stepprof.ring().recent()) <= 32
        assert stepprof.ring().live() == []
        assert eng.allocator.num_used == 0, "KV leak under flood"
        eng.allocator.check()
        eng._audit()
    finally:
        pt.set_flags({"llm_seqtrace_ring": 256, "llm_step_ring": 256})
    print("  flood bounding OK")

    from paddle_tpu.observability import seqtrace, stepprof
    seqtrace.ring().reset()
    stepprof.ring().reset()
    print("self-test OK")
    return 0


# ----------------------------------------------------------------- main

def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        description="LLM serving latency attribution "
                    "(seq timelines x step records)")
    ap.add_argument("--url", help="live exporter base URL "
                                  "(fetches /llm/seqs + /llm/steps)")
    ap.add_argument("--input", help="JSON file of endpoint dumps")
    ap.add_argument("--pct", type=float, default=99.0,
                    help="gap percentile threshold (default 99)")
    ap.add_argument("--threshold-ms", type=float, default=None,
                    help="absolute gap threshold, overrides --pct")
    ap.add_argument("--top", type=int, default=20,
                    help="findings to print (default 20)")
    ap.add_argument("--json", action="store_true",
                    help="emit the report as JSON")
    ap.add_argument("--chrome", metavar="OUT",
                    help="also write the joined chrome trace here")
    ap.add_argument("--self-test", action="store_true")
    args = ap.parse_args(argv)
    if args.self_test:
        return self_test()
    if args.url:
        tls, steps = load_url(args.url)
    elif args.input:
        tls, steps = load_file(args.input)
    else:
        tls, steps = load_rings()
    rep = analyze(tls, steps, threshold_ms=args.threshold_ms,
                  pct=args.pct)
    if args.chrome:
        with open(args.chrome, "w") as f:
            json.dump(chrome_trace(tls, steps), f)
        print(f"chrome trace -> {args.chrome}", file=sys.stderr)
    print(json.dumps(rep, indent=1) if args.json
          else render(rep, top=args.top))
    return 0


if __name__ == "__main__":
    sys.exit(main())
