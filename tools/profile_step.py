"""Capture an on-chip profile of one bench model's train step and
aggregate op self-times from the perfetto trace.

Usage: python tools/profile_step.py {bert|resnet} [batch]
Writes profiles/<model>/... and prints the top-30 ops by total duration
plus a category rollup (matmul/conv/copy/transpose/elementwise/other) —
the same aggregation the round-2 README profile used.
"""

from __future__ import annotations

import glob
import gzip
import json
import os
import sys
from collections import defaultdict

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def capture(model: str, batch: int) -> str:
    import numpy as np

    import jax
    import paddle_tpu as pt
    from paddle_tpu.static import TrainStep

    outdir = os.path.join(ROOT, "profiles", model)
    os.makedirs(outdir, exist_ok=True)
    rng = np.random.default_rng(0)
    if model == "bert":
        from paddle_tpu.models import (BertConfig, BertForPretraining,
                                       pretraining_loss)
        config = BertConfig()
        seq = 512
        pt.seed(0)
        m = BertForPretraining(config)
        m.to(dtype="bfloat16")
        o = pt.optimizer.AdamW(learning_rate=1e-4, weight_decay=0.01)
        step = TrainStep(m, o, lambda out, a, b: pretraining_loss(out, a, b))
        ids = rng.integers(0, config.vocab_size, (batch, seq)).astype("int32")
        mlm = rng.integers(0, config.vocab_size, (batch, seq)).astype("int64")
        nsp = rng.integers(0, 2, (batch,)).astype("int64")
        run = lambda: step(ids, labels=(mlm, nsp))
    else:
        import jax.numpy as jnp
        from paddle_tpu.models.resnet import resnet50
        layout = os.environ.get("PT_PROF_LAYOUT", "NCHW")
        pt.seed(0)
        m = resnet50(data_format=layout)
        m.to(dtype="bfloat16")
        o = pt.optimizer.Momentum(learning_rate=0.1, momentum=0.9)
        step = TrainStep(m, o, lambda out, t:
                         pt.nn.functional.cross_entropy(out, t))
        x = rng.normal(0, 1, (batch, 3, 224, 224))
        if layout == "NHWC":
            x = np.transpose(x, (0, 2, 3, 1))
        x = jnp.asarray(x, jnp.bfloat16)
        y = rng.integers(0, 1000, (batch,)).astype("int64")
        run = lambda: step(x, labels=y)

    # warm up (compile) outside the trace
    for _ in range(3):
        float(run()["loss"])
    with jax.profiler.trace(outdir):
        for _ in range(5):
            r = run()
        float(r["loss"])
    return outdir


def aggregate(outdir: str) -> None:
    traces = sorted(glob.glob(os.path.join(
        outdir, "**", "*.trace.json.gz"), recursive=True))
    if not traces:
        # a profiler stage with no trace produced no data — exit nonzero
        # so capture_all records it not-ok and the watcher retries
        print(f"no trace.json.gz under {outdir}", file=sys.stderr)
        sys.exit(2)
    with gzip.open(traces[-1], "rt") as f:
        data = json.load(f)
    events = data.get("traceEvents", [])
    # The device process exposes three lanes (Steps / XLA Modules /
    # XLA Ops); the first two are aggregates of the third, so summing
    # every device event double-counts the whole step (the round-4
    # rollup did exactly that and mis-ranked BN reductions over conv).
    # Keep ONLY the "XLA Ops" lane and trust its hlo_category metadata
    # over name-substring guessing (fusion names hide the conv inside).
    pid_names = {e.get("pid"): e.get("args", {}).get("name", "")
                 for e in events if e.get("ph") == "M"
                 and e.get("name") == "process_name"}
    device_pids = {p for p, n in pid_names.items()
                   if "TPU" in n or "tpu" in n or "/device" in n.lower()
                   or "XLA" in n}
    op_tids = {(e.get("pid"), e.get("tid"))
               for e in events if e.get("ph") == "M"
               and e.get("name") == "thread_name"
               and e.get("args", {}).get("name") == "XLA Ops"}
    if not op_tids:
        # without lane metadata the filter below would silently revert
        # to summing Steps + Modules + Ops (the double-count this
        # rewrite removed) — refuse to print authoritative-looking
        # numbers instead
        print("trace has no 'XLA Ops' thread_name metadata; cannot "
              "aggregate reliably (profiler version mismatch?)",
              file=sys.stderr)
        sys.exit(2)
    durs: dict = defaultdict(float)
    counts: dict = defaultdict(int)
    cats: dict = defaultdict(float)
    total = 0.0
    for e in events:
        if e.get("ph") != "X":
            continue
        if device_pids and e.get("pid") not in device_pids:
            continue
        if (e.get("pid"), e.get("tid")) not in op_tids:
            continue
        name = e.get("name", "?")
        d = float(e.get("dur", 0.0))
        durs[name] += d
        counts[name] += 1
        cats[e.get("args", {}).get("hlo_category", "?")] += d
        total += d
    # per-step divisor: one event per step on the "XLA Modules" lane
    mod_tids = {(e.get("pid"), e.get("tid"))
                for e in events if e.get("ph") == "M"
                and e.get("name") == "thread_name"
                and e.get("args", {}).get("name") == "XLA Modules"}
    steps = sum(1 for e in events if e.get("ph") == "X"
                and (e.get("pid"), e.get("tid")) in mod_tids)
    if not steps:
        print("warning: no 'XLA Modules' step events; reporting "
              "whole-trace totals as one step", file=sys.stderr)
        steps = 1
    print(f"\n== device op time rollup (total {total / 1e3:.2f} ms, "
          f"{steps} steps, {total / steps / 1e3:.2f} ms/step) ==")
    for c, d in sorted(cats.items(), key=lambda kv: -kv[1]):
        print(f"  {c:24s} {d / steps / 1e3:9.3f} ms/step "
              f"{d / total * 100:5.1f}%")
    print("\n== top 30 ops by total duration ==")
    for name, d in sorted(durs.items(), key=lambda kv: -kv[1])[:30]:
        print(f"  {d / steps / 1e3:9.3f} ms/step x{counts[name]:<5d}"
              f" {name[:100]}")


def main() -> None:
    # same probe + rc=3 fast-abort protocol as bench.py, so the watcher
    # can tell a tunnel outage from a real failed attempt
    sys.path.insert(0, ROOT)
    from bench import _probe_backend, acquire_chip_lock
    acquire_chip_lock("profile")
    if not _probe_backend():
        print("[profile] backend unreachable; aborting (rc=3)",
              file=sys.stderr)
        sys.exit(3)
    from paddle_tpu.core.place import accelerator_available
    if not accelerator_available():
        print("[profile] no accelerator device (CPU fallback would "
              "record a host-only trace); aborting", file=sys.stderr)
        sys.exit(3)
    model = sys.argv[1] if len(sys.argv) > 1 else "bert"
    batch = int(sys.argv[2]) if len(sys.argv) > 2 else \
        (8 if model == "bert" else 64)
    outdir = capture(model, batch)
    aggregate(outdir)


if __name__ == "__main__":
    main()
