"""Capture an on-chip profile of one bench model's train step and
aggregate op self-times from the perfetto trace.

Usage: python tools/profile_step.py {bert|resnet} [batch]
Writes profiles/<model>/... and prints the top-30 ops by total duration
plus a category rollup (matmul/conv/copy/transpose/elementwise/other) —
the same aggregation the round-2 README profile used.
"""

from __future__ import annotations

import os
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def capture(model: str, batch: int) -> str:
    import numpy as np

    import jax
    import paddle_tpu as pt
    from paddle_tpu.static import TrainStep

    outdir = os.path.join(ROOT, "profiles", model)
    os.makedirs(outdir, exist_ok=True)
    rng = np.random.default_rng(0)
    if model == "bert":
        from paddle_tpu.models import (BertConfig, BertForPretraining,
                                       pretraining_loss)
        config = BertConfig()
        seq = 512
        pt.seed(0)
        m = BertForPretraining(config)
        m.to(dtype="bfloat16")
        o = pt.optimizer.AdamW(learning_rate=1e-4, weight_decay=0.01)
        step = TrainStep(m, o, lambda out, a, b: pretraining_loss(out, a, b))
        ids = rng.integers(0, config.vocab_size, (batch, seq)).astype("int32")
        mlm = rng.integers(0, config.vocab_size, (batch, seq)).astype("int64")
        nsp = rng.integers(0, 2, (batch,)).astype("int64")
        run = lambda: step(ids, labels=(mlm, nsp))
    else:
        import jax.numpy as jnp
        from paddle_tpu.models.resnet import resnet50
        layout = os.environ.get("PT_PROF_LAYOUT", "NCHW")
        pt.seed(0)
        m = resnet50(data_format=layout)
        m.to(dtype="bfloat16")
        o = pt.optimizer.Momentum(learning_rate=0.1, momentum=0.9)
        step = TrainStep(m, o, lambda out, t:
                         pt.nn.functional.cross_entropy(out, t))
        x = rng.normal(0, 1, (batch, 3, 224, 224))
        if layout == "NHWC":
            x = np.transpose(x, (0, 2, 3, 1))
        x = jnp.asarray(x, jnp.bfloat16)
        y = rng.integers(0, 1000, (batch,)).astype("int64")
        run = lambda: step(x, labels=y)

    # warm up (compile) outside the trace
    for _ in range(3):
        float(run()["loss"])
    with jax.profiler.trace(outdir):
        for _ in range(5):
            r = run()
        float(r["loss"])
    return outdir


def aggregate(outdir: str) -> None:
    # parsing/rollup shared with tools/trace_report.py:
    # paddle_tpu.observability.trace_agg (keeps the round-4 lesson in
    # one place: only the "XLA Ops" lane, hlo_category over name
    # guessing)
    from paddle_tpu.observability import trace_agg

    traces = trace_agg.find_xla_traces(outdir)
    if not traces:
        # a profiler stage with no trace produced no data — exit nonzero
        # so capture_all records it not-ok and the watcher retries
        print(f"no trace.json.gz under {outdir}", file=sys.stderr)
        sys.exit(2)
    events = trace_agg.load_trace_events(traces[-1])
    try:
        rollup = trace_agg.xla_op_rollup(events)
    except trace_agg.TraceFormatError as e:
        # without lane metadata the aggregation would silently revert
        # to summing Steps + Modules + Ops (the double-count the
        # round-4 rewrite removed) — refuse to print
        # authoritative-looking numbers instead
        print(str(e), file=sys.stderr)
        sys.exit(2)
    if not rollup["steps"]:
        print("warning: no 'XLA Modules' step events; reporting "
              "whole-trace totals as one step", file=sys.stderr)
    print()
    print(trace_agg.format_xla_rollup(rollup, top=30))


def main() -> None:
    # same probe + rc=3 fast-abort protocol as bench.py, so the watcher
    # can tell a tunnel outage from a real failed attempt
    sys.path.insert(0, ROOT)
    from bench import _probe_backend, acquire_chip_lock
    acquire_chip_lock("profile")
    if not _probe_backend():
        print("[profile] backend unreachable; aborting (rc=3)",
              file=sys.stderr)
        sys.exit(3)
    from paddle_tpu.core.place import accelerator_available
    if not accelerator_available():
        print("[profile] no accelerator device (CPU fallback would "
              "record a host-only trace); aborting", file=sys.stderr)
        sys.exit(3)
    model = sys.argv[1] if len(sys.argv) > 1 else "bert"
    batch = int(sys.argv[2]) if len(sys.argv) > 2 else \
        (8 if model == "bert" else 64)
    outdir = capture(model, batch)
    aggregate(outdir)


if __name__ == "__main__":
    main()
