"""Real-chip performance experiments (run when a TPU is reachable).

Each experiment isolates one hypothesis from the round-2 profile of the
BERT train step (34.6 ms/step wall, 31.8 ms device: 58% matmul fusions,
~19% per-buffer async copies/slices — ~1.1k copy + 1.9k slice ops/step
— 5.5% dropout-mask compare fusions, 5% loss-region reductions, 2.2%
rng-bit-generator). Usage:

    python tools/perf_lab.py leafcount   # runtime cost vs #state leaves
    python tools/perf_lab.py fused      # fused vs per-leaf opt state
    python tools/perf_lab.py batch      # batch-size sweep
    python tools/perf_lab.py all
"""

from __future__ import annotations

import sys
import time


def log(msg):
    print(f"[perf_lab] {msg}", file=sys.stderr, flush=True)


def _sync(x):
    import jax
    jax.block_until_ready(x)
    # remote-dispatch backends need a value fetch for a hard sync
    import numpy as np
    leaf = jax.tree.leaves(x)[0]
    np.asarray(leaf.ravel()[0])


def exp_leafcount():
    """Hypothesis: the runtime charges ~2-4us per donated buffer per
    step. Same total bytes split into N leaves, trivial update."""
    import jax
    import jax.numpy as jnp

    total = 64 * 1024 * 1024 // 4  # 64 MB of f32
    for n in (8, 64, 256, 1024):
        per = total // n
        state = {f"p{i}": jnp.zeros((per,), jnp.float32)
                 for i in range(n)}
        step_d = jax.jit(lambda s: {k: v + 1.0 for k, v in s.items()},
                         donate_argnums=(0,))
        for _ in range(3):
            state = step_d(state)
        _sync(state)
        t0 = time.perf_counter()
        iters = 50
        for _ in range(iters):
            state = step_d(state)
        _sync(state)
        dt = (time.perf_counter() - t0) / iters
        log(f"leaves={n:5d}: {dt * 1e6:8.1f} us/step "
            f"({dt * 1e6 / n:6.2f} us/leaf)")


def _repo_root():
    import os
    return os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def exp_fused():
    """BERT step: per-leaf vs fused optimizer state, measured."""
    import os

    import jax

    os.environ["PT_BENCH_FUSED"] = ""
    sys.path.insert(0, _repo_root())
    import bench
    on_accel = any(d.platform in ("tpu", "axon") for d in jax.devices())
    if not on_accel:
        log("no accelerator: running the tiny CPU shape (numbers only "
            "meaningful on a real chip)")
    bench.bench_bert(on_accel=on_accel)


def exp_batch():
    import numpy as np

    import paddle_tpu as pt
    from paddle_tpu.models import (BertConfig, BertForPretraining,
                                   pretraining_loss)
    from paddle_tpu.static import TrainStep

    config = BertConfig()
    for batch in (4, 8, 16):
        pt.seed(0)
        model = BertForPretraining(config)
        model.to(dtype="bfloat16")
        opt = pt.optimizer.AdamW(learning_rate=1e-4, weight_decay=0.01)
        step = TrainStep(model, opt,
                         lambda out, a, b: pretraining_loss(out, a, b))
        rng = np.random.default_rng(0)
        seq = 512
        ids = rng.integers(0, config.vocab_size, (batch, seq)) \
            .astype(np.int32)
        mlm = rng.integers(0, config.vocab_size, (batch, seq)) \
            .astype(np.int64)
        nsp = rng.integers(0, 2, (batch,)).astype(np.int64)
        sys.path.insert(0, _repo_root())
        from bench import warmup_and_time
        dt = warmup_and_time(lambda: step(ids, labels=(mlm, nsp)), 20)
        log(f"batch={batch}: {dt * 1e3:.1f} ms/step "
            f"{batch * seq / dt:.0f} tok/s")
        del model, step


def main():
    which = sys.argv[1] if len(sys.argv) > 1 else "all"
    known = {"leafcount", "batch", "fused", "all"}
    if which not in known:
        raise SystemExit(f"unknown experiment {which!r}; pick from "
                         f"{sorted(known)}")
    # fail fast if the accelerator tunnel is wedged (bench.py's probe,
    # the round-1 rc=124 failure mode)
    sys.path.insert(0, _repo_root())
    import bench
    if not bench._probe_backend(attempts=1, timeout_s=120):
        raise SystemExit("accelerator backend unreachable (tunnel "
                         "wedged?); aborting fast")
    import jax
    import os
    jax.config.update("jax_compilation_cache_dir",
                      os.path.join(_repo_root(), ".jax_cache"))
    log(f"backend={jax.default_backend()} devices={jax.devices()}")
    if which in ("leafcount", "all"):
        exp_leafcount()
    if which in ("batch", "all"):
        exp_batch()
    if which in ("fused", "all"):
        exp_fused()


if __name__ == "__main__":
    main()
