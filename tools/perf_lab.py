"""Real-chip performance experiments (run when a TPU is reachable).

Each experiment isolates one hypothesis from the round-2 profile of the
BERT train step (34.6 ms/step wall, 31.8 ms device: 58% matmul fusions,
~19% per-buffer async copies/slices — ~1.1k copy + 1.9k slice ops/step
— 5.5% dropout-mask compare fusions, 5% loss-region reductions, 2.2%
rng-bit-generator). Usage:

    python tools/perf_lab.py leafcount   # runtime cost vs #state leaves
    python tools/perf_lab.py fused      # fused vs per-leaf opt state
    python tools/perf_lab.py batch      # batch-size sweep
    python tools/perf_lab.py hlostats   # CPU-only: copy/transpose counts
    python tools/perf_lab.py all        # all CHIP experiments (hlostats
                                        # is CPU-only and must run in its
                                        # own process: it pins the
                                        # platform to cpu before init)
"""

from __future__ import annotations

import sys
import time


def log(msg):
    print(f"[perf_lab] {msg}", file=sys.stderr, flush=True)


def _sync(x):
    import jax
    jax.block_until_ready(x)
    # remote-dispatch backends need a value fetch for a hard sync
    import numpy as np
    leaf = jax.tree.leaves(x)[0]
    np.asarray(leaf.ravel()[0])


def exp_leafcount():
    """Hypothesis: the runtime charges ~2-4us per donated buffer per
    step. Same total bytes split into N leaves, trivial update."""
    import jax
    import jax.numpy as jnp

    total = 64 * 1024 * 1024 // 4  # 64 MB of f32
    for n in (8, 64, 256, 1024):
        per = total // n
        state = {f"p{i}": jnp.zeros((per,), jnp.float32)
                 for i in range(n)}
        step_d = jax.jit(lambda s: {k: v + 1.0 for k, v in s.items()},
                         donate_argnums=(0,))
        for _ in range(3):
            state = step_d(state)
        _sync(state)
        t0 = time.perf_counter()
        iters = 50
        for _ in range(iters):
            state = step_d(state)
        _sync(state)
        dt = (time.perf_counter() - t0) / iters
        log(f"leaves={n:5d}: {dt * 1e6:8.1f} us/step "
            f"({dt * 1e6 / n:6.2f} us/leaf)")


def _repo_root():
    import os
    return os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def exp_fused():
    """BERT step: per-leaf vs fused optimizer state, measured."""
    import os

    import jax

    os.environ["PT_BENCH_FUSED"] = ""
    sys.path.insert(0, _repo_root())
    import bench
    from paddle_tpu.core.place import accelerator_available
    on_accel = accelerator_available()
    if not on_accel:
        log("no accelerator: running the tiny CPU shape (numbers only "
            "meaningful on a real chip)")
    bench.bench_bert(on_accel=on_accel)


def exp_batch():
    import numpy as np

    import paddle_tpu as pt
    from paddle_tpu.models import (BertConfig, BertForPretraining,
                                   pretraining_loss)
    from paddle_tpu.static import TrainStep

    config = BertConfig()
    for batch in (4, 8, 16):
        pt.seed(0)
        model = BertForPretraining(config)
        model.to(dtype="bfloat16")
        opt = pt.optimizer.AdamW(learning_rate=1e-4, weight_decay=0.01)
        step = TrainStep(model, opt,
                         lambda out, a, b: pretraining_loss(out, a, b))
        rng = np.random.default_rng(0)
        seq = 512
        ids = rng.integers(0, config.vocab_size, (batch, seq)) \
            .astype(np.int32)
        mlm = rng.integers(0, config.vocab_size, (batch, seq)) \
            .astype(np.int64)
        nsp = rng.integers(0, 2, (batch,)).astype(np.int64)
        sys.path.insert(0, _repo_root())
        from bench import warmup_and_time
        dt = warmup_and_time(lambda: step(ids, labels=(mlm, nsp)), 20)
        log(f"batch={batch}: {dt * 1e3:.1f} ms/step "
            f"{batch * seq / dt:.0f} tok/s")
        del model, step


def exp_hlostats():
    """Structural evidence WITHOUT a chip: compile small-config train
    steps on CPU and count buffer-shuffling ops (copy / transpose /
    bitcast / parameters) in the optimized HLO. The per-leaf vs fused
    optimizer-state gap and the NCHW vs NHWC transpose burden both show
    up here before a single chip-second is spent (the chip decides the
    final flag; this decides what's worth timing)."""
    import collections
    import re

    import jax
    import numpy as np

    import paddle_tpu as pt
    from paddle_tpu.static import TrainStep

    jax.config.update("jax_platforms", "cpu")

    def hlo_counts(text):
        # [\w-]+ so hyphenated async/collective ops (copy-start,
        # dynamic-slice, all-reduce, rng-bit-generator) are counted —
        # on TPU HLO those carry the buffer traffic this tool exists
        # to measure. copy-start/copy-done fold into "copy".
        ops = collections.Counter()
        for m in re.finditer(
                r"^\s*(?:ROOT )?%?[\w.\-]+ = [^=]*? ([\w-]+)\(",
                text, re.M):
            name = m.group(1)
            if name in ("copy-start", "copy-done"):
                name = "copy"
            ops[name] += 1
        return ops

    def entry_params(text):
        # count parameters of the ENTRY computation only — nested
        # fusion/reduce subcomputations each carry their own
        # parameter() lines and would swamp the state-leaf count
        m = re.search(r"^ENTRY [^{]*\{(.*?)^\}", text, re.M | re.S)
        body = m.group(1) if m else text
        return len(re.findall(r"= [^=]*? parameter\(", body))

    def report(name, text):
        ops = hlo_counts(text)
        interesting = {k: ops[k] for k in
                       ("copy", "transpose", "bitcast", "fusion",
                        "convolution", "dot", "reduce", "dynamic-slice",
                        "dynamic-update-slice") if ops[k]}
        log(f"{name}: entry_params={entry_params(text)} {interesting}")
        return ops

    # --- BERT-small step: per-leaf vs fused optimizer state
    from paddle_tpu.models import (BertConfig, BertForPretraining,
                                   pretraining_loss)
    config = BertConfig(num_hidden_layers=4, hidden_size=256,
                        num_attention_heads=4, intermediate_size=1024,
                        vocab_size=4096, max_position_embeddings=128)
    rng = np.random.default_rng(0)
    ids = rng.integers(0, 4096, (2, 64)).astype(np.int32)
    mlm = rng.integers(0, 4096, (2, 64)).astype(np.int64)
    nsp = rng.integers(0, 2, (2,)).astype(np.int64)
    results = {}
    for fused in (False, True):
        pt.seed(0)
        m = BertForPretraining(config)
        m.to(dtype="bfloat16")
        o = pt.optimizer.AdamW(learning_rate=1e-4, weight_decay=0.01,
                               fused_state=fused)
        step = TrainStep(m, o, lambda out, a, b:
                         pretraining_loss(out, a, b))
        text = step.compiled_hlo(ids, labels=(mlm, nsp))
        results[fused] = report(f"bert4L fused={fused}", text)
    cp, ct = results[False]["copy"], results[True]["copy"]
    log(f"bert4L: fused state changes HLO copies {cp} -> {ct}")

    # --- ResNet block stack: NCHW vs NHWC transpose burden
    from paddle_tpu.models.resnet import BasicBlock, ResNet
    x = rng.normal(0, 1, (2, 3, 32, 32)).astype(np.float32)
    y = rng.integers(0, 10, (2,)).astype(np.int64)
    for df in ("NCHW", "NHWC"):
        pt.seed(0)
        net = ResNet(BasicBlock, [1, 1, 1, 1], num_classes=10,
                     data_format=df)
        opt = pt.optimizer.Momentum(learning_rate=0.1, momentum=0.9)
        step = TrainStep(net, opt, lambda out, t:
                         pt.nn.functional.cross_entropy(out, t))
        data = x if df == "NCHW" else np.transpose(x, (0, 2, 3, 1))
        text = step.compiled_hlo(data, labels=y)
        report(f"resnet18-thin {df}", text)


def main():
    which = sys.argv[1] if len(sys.argv) > 1 else "all"
    known = {"leafcount", "batch", "fused", "hlostats", "all"}
    if which not in known:
        raise SystemExit(f"unknown experiment {which!r}; pick from "
                         f"{sorted(known)}")
    sys.path.insert(0, _repo_root())
    if which == "hlostats":
        # CPU-only experiment: no tunnel needed
        exp_hlostats()
        return
    # fail fast if the accelerator tunnel is wedged (bench.py's probe,
    # the round-1 rc=124 failure mode)
    import bench
    if not bench._probe_backend(attempts=1, timeout_s=120):
        raise SystemExit("accelerator backend unreachable (tunnel "
                         "wedged?); aborting fast")
    import jax
    from paddle_tpu.sysconfig import enable_compile_cache
    enable_compile_cache()
    log(f"backend={jax.default_backend()} devices={jax.devices()}")
    if which in ("leafcount", "all"):
        exp_leafcount()
    if which in ("batch", "all"):
        exp_batch()
    if which in ("fused", "all"):
        exp_fused()


if __name__ == "__main__":
    main()
