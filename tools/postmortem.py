#!/usr/bin/env python3
"""postmortem — one-command wedge-forensics bundle + human report.

Collects everything a "why is this process stuck / why did it die"
investigation needs from a live observability exporter
(observability/server.py) into a single bundle directory: flags and
versions (``/varz``), the Prometheus metrics page, the flight-recorder
ring, the serving flight deck (``/llm/seqs``, ``/llm/steps``,
``/requests``), the SLO/alert state, goodput, health, and — the hang
doctor's half (observability/stacks.py) — the instant all-thread stack
dump plus the sampling profiler's collapsed and Chrome-flame exports.

``--fleet`` additionally pulls the PR 6 federation plane: the merged
``/fleet`` views and the ``/fleet/stacks`` fan-out (every registered
worker's live stacks through the aggregator), splitting per-host
answers into ``fleet/hosts/<host>/``.

``render`` prints the human report: the wedged/culprit thread first
(the last ``hang_diagnosis`` flight event when one exists, else the
blocked threads from the live dump), then health, the last flight
events, the top sampled stacks, and the alert headline. ``render``
with ``--url`` collects first — one command from wedge to report.

Usage:
  python tools/postmortem.py collect --url HOST:PORT [--out DIR]
                                     [--fleet] [--tar]
  python tools/postmortem.py render  BUNDLE_DIR
  python tools/postmortem.py [--fleet] render --url HOST:PORT
  python tools/postmortem.py --self-test

Bundle layout (docs/observability.md, "Hang doctor"):
  manifest.json  varz.json  metrics.prom  healthz.json  flight.json
  goodput.json  slo.json  alerts.json  requests.json  llm_seqs.json
  llm_steps.json  stacks.json  stacks_collapsed.txt  stacks_flame.json
  fleet/{fleet,health,goodput,alerts,stacks}.json
  fleet/hosts/<host>/stacks.json
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import sys
import tempfile
import time
import urllib.error
import urllib.request

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if ROOT not in sys.path:  # runnable from any cwd
    sys.path.insert(0, ROOT)

# endpoint -> bundle file; .prom keeps the raw exposition text
_ENDPOINTS = [
    ("/varz", "varz.json"),
    ("/metrics", "metrics.prom"),
    ("/healthz", "healthz.json"),
    ("/flight", "flight.json"),
    ("/goodput", "goodput.json"),
    ("/slo", "slo.json"),
    ("/alerts", "alerts.json"),
    ("/requests?n=64", "requests.json"),
    ("/llm/seqs?n=64", "llm_seqs.json"),
    ("/llm/steps?n=64", "llm_steps.json"),
    ("/stacks", "stacks.json"),
    ("/stacks?format=collapsed", "stacks_collapsed.txt"),
    ("/stacks?format=flame", "stacks_flame.json"),
]

_FLEET_ENDPOINTS = [
    ("/fleet?format=json", "fleet.json"),
    ("/fleet/health", "health.json"),
    ("/fleet/goodput", "goodput.json"),
    ("/fleet/alerts", "alerts.json"),
    ("/fleet/stacks", "stacks.json"),
]


def _fetch(url: str, timeout_s: float = 10.0):
    """(status, body_bytes) — non-2xx bodies are still forensics
    (e.g. a 503 /healthz is exactly what we came for)."""
    try:
        with urllib.request.urlopen(url, timeout=timeout_s) as r:
            return r.status, r.read()
    except urllib.error.HTTPError as e:
        return e.code, e.read()


def collect(url: str, out_dir: str, fleet: bool = False,
            tar: bool = False, quiet: bool = False) -> str:
    """Pull every endpoint from ``url`` (host:port) into ``out_dir``;
    returns the bundle path (the .tar.gz path with ``tar=True``). A
    failing endpoint degrades to an ``<name>.error`` file — a half
    bundle from a half-dead process beats no bundle."""
    base = url if "//" in url else f"http://{url}"
    base = base.rstrip("/")
    os.makedirs(out_dir, exist_ok=True)
    manifest = {"created_unix": time.time(), "url": url,
                "collector_python": sys.version.split()[0],
                "fleet": fleet, "files": [], "errors": []}

    def grab(path, fname, sub=""):
        dest_dir = os.path.join(out_dir, sub) if sub else out_dir
        os.makedirs(dest_dir, exist_ok=True)
        dest = os.path.join(dest_dir, fname)
        rel = os.path.join(sub, fname) if sub else fname
        try:
            status, body = _fetch(base + path)
        except Exception as e:  # noqa: BLE001 — degrade per endpoint
            with open(dest + ".error", "w") as f:
                f.write(f"{type(e).__name__}: {e}\n")
            manifest["errors"].append({"path": path,
                                       "error": f"{type(e).__name__}: {e}"})
            return None
        with open(dest, "wb") as f:
            f.write(body)
        manifest["files"].append({"file": rel, "path": path,
                                  "status": status})
        return body

    for path, fname in _ENDPOINTS:
        grab(path, fname)
    if fleet:
        for path, fname in _FLEET_ENDPOINTS:
            body = grab(path, fname, sub="fleet")
            if fname == "stacks.json" and body:
                try:
                    hosts = json.loads(body).get("hosts", {})
                except ValueError:
                    hosts = {}
                for host, rec in hosts.items():
                    safe = "".join(c if c.isalnum() or c in "-_."
                                   else "_" for c in host)
                    hdir = os.path.join("fleet", "hosts", safe)
                    os.makedirs(os.path.join(out_dir, hdir),
                                exist_ok=True)
                    with open(os.path.join(out_dir, hdir,
                                           "stacks.json"), "w") as f:
                        json.dump(rec, f, indent=1, default=str)
    # versions of the *observed* process live in varz.json; mirror
    # them into the manifest for one-file triage
    try:
        with open(os.path.join(out_dir, "varz.json")) as f:
            varz = json.load(f)
        manifest["versions"] = varz.get("versions")
        manifest["flags"] = varz.get("flags")
    except (OSError, ValueError):
        pass
    with open(os.path.join(out_dir, "manifest.json"), "w") as f:
        json.dump(manifest, f, indent=1, sort_keys=True, default=str)
    if not quiet:
        print(f"[postmortem] bundle at {out_dir} "
              f"({len(manifest['files'])} files, "
              f"{len(manifest['errors'])} errors)")
    if tar:
        archive = shutil.make_archive(out_dir.rstrip("/"), "gztar",
                                      root_dir=out_dir)
        if not quiet:
            print(f"[postmortem] archived to {archive}")
        return archive
    return out_dir


# ------------------------------------------------------------- render

def _load_json(bundle: str, *parts):
    try:
        with open(os.path.join(bundle, *parts)) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def _fmt_thread(t) -> str:
    bits = [f"{t.get('name')}: {t.get('state', '?')}"]
    frame = t.get("frame") or t.get("top")
    if frame:
        bits.append(f"at {frame}")
    if t.get("lock"):
        bits.append(f"lock={t['lock']}")
        if t.get("guards"):
            bits.append(f"guards={','.join(t['guards'])}")
    if t.get("same_top_s") is not None:
        bits.append(f"same top frame for {t['same_top_s']}s")
    return "  ".join(bits)


def render(bundle: str, out=None) -> int:
    """Print the human report; returns 0, or 1 when the path holds no
    readable bundle."""
    out = out or sys.stdout
    manifest = _load_json(bundle, "manifest.json")
    if manifest is None:
        print(f"postmortem: no manifest.json under {bundle}",
              file=sys.stderr)
        return 1
    w = out.write
    w(f"== postmortem: {bundle} ==\n")
    w(f"collected {time.strftime('%Y-%m-%d %H:%M:%S', time.localtime(manifest.get('created_unix', 0)))}"
      f" from {manifest.get('url')}\n")
    versions = manifest.get("versions") or {}
    if versions:
        w("versions: " + "  ".join(f"{k}={v}" for k, v
                                   in sorted(versions.items())) + "\n")

    # -- the wedged thread, first ----------------------------------------
    flight = _load_json(bundle, "flight.json") or {}
    events = flight.get("events", [])
    diag = next((e for e in reversed(events)
                 if e.get("kind") == "hang_diagnosis"), None)
    w("\n-- wedged thread --\n")
    if diag is not None and diag.get("culprit"):
        c = diag["culprit"]
        w(f"CULPRIT (hang_diagnosis, source={diag.get('source')}): "
          f"thread '{c.get('thread')}' {c.get('state')} "
          f"at {c.get('frame')}\n")
        if c.get("lock"):
            w(f"  contended lock: {c['lock']}"
              + (f" (guards: {', '.join(c['guards'])})"
                 if c.get("guards") else "") + "\n")
        for fr in (c.get("frames") or [])[:8]:
            w(f"    {fr}\n")
    else:
        stacks = _load_json(bundle, "stacks.json") or {}
        blocked = [t for t in stacks.get("threads", [])
                   if t.get("state", "running") != "running"]
        if blocked:
            for t in blocked:
                w("  " + _fmt_thread(t) + "\n")
        else:
            w("  no hang_diagnosis recorded and no blocked threads "
              "in the live dump\n")

    # -- health ----------------------------------------------------------
    health = _load_json(bundle, "healthz.json") or {}
    w("\n-- health --\n")
    w(f"  status={health.get('status', '?')}"
      f"  heartbeat_age_s={health.get('heartbeat_age_s')}\n")
    serving = health.get("serving")
    if serving:
        for e in serving.get("engines", []):
            w(f"  engine: stalled={e.get('stalled')} "
              f"last_step_age_s={e.get('last_step_age_s')} "
              f"stalls_total={e.get('stalls_total')}\n")

    # -- last flight events ----------------------------------------------
    w("\n-- last flight events --\n")
    for e in events[-12:]:
        kind = e.get("kind", "?")
        extras = {k: v for k, v in e.items()
                  if k not in ("kind", "ts_unix", "threads")}
        brief = json.dumps(extras, default=str)
        w(f"  {kind:24s} {brief[:120]}\n")
    if not events:
        w("  (flight ring empty)\n")

    # -- top sampled stacks ----------------------------------------------
    w("\n-- top sampled stacks --\n")
    try:
        with open(os.path.join(bundle, "stacks_collapsed.txt")) as f:
            lines = [ln for ln in f.read().splitlines() if ln]
    except OSError:
        lines = []
    if lines:
        def count(ln):
            try:
                return int(ln.rsplit(" ", 1)[1])
            except (IndexError, ValueError):
                return 0
        for ln in sorted(lines, key=count, reverse=True)[:8]:
            w(f"  {ln[:160]}\n")
    else:
        w("  (sampler off or no samples)\n")

    # -- alerts ----------------------------------------------------------
    alerts = _load_json(bundle, "alerts.json") or {}
    w("\n-- alerts --\n")
    w(f"  worst_state={alerts.get('worst_state', '?')}\n")
    for a in alerts.get("alerts", []):
        if a.get("state") not in (None, "inactive"):
            w(f"  {a.get('slo')}: {a.get('state')} "
              f"budget_remaining={a.get('budget_remaining')}\n")

    # -- fleet -----------------------------------------------------------
    fstacks = _load_json(bundle, "fleet", "stacks.json")
    if fstacks is not None:
        w("\n-- fleet stacks --\n")
        for host, rec in sorted((fstacks.get("hosts") or {}).items()):
            if rec.get("error"):
                w(f"  {host}: UNREACHABLE ({rec['error']})\n")
                continue
            threads = (rec.get("stacks") or {}).get("threads", [])
            blocked = [t for t in threads
                       if t.get("state", "running") != "running"]
            pick = blocked[0] if blocked else (threads[0] if threads
                                               else None)
            w(f"  {host}: " + (_fmt_thread(pick) if pick
                               else "(no threads)") + "\n")
    w("\n")
    return 0


# ---------------------------------------------------------- self-test

def self_test() -> int:
    """No-accelerator CI check: boot an exporter, stage a diagnosable
    wedge (hang_diagnosis + sampled profile + a pushed fleet
    snapshot), collect a --fleet bundle over HTTP, render it, and
    assert the report names the culprit thread."""
    import threading

    import paddle_tpu as pt
    from paddle_tpu.observability import fleet as _fleet
    from paddle_tpu.observability import flight as _flight
    from paddle_tpu.observability import metrics as _metrics
    from paddle_tpu.observability import server as _server
    from paddle_tpu.observability import stacks as _stacks

    _metrics.set_enabled(True)
    srv = _server.ObservabilityServer(0)
    tmp = tempfile.mkdtemp(prefix="postmortem_selftest_")
    try:
        _metrics.gauge("observability_server_port",
                       "TCP port of the live observability HTTP "
                       "exporter", always=True).set(float(srv.port))
        _flight.record("selftest_step", step=1)
        # a real blocked thread for capture + diagnosis to find
        release = threading.Event()
        t = threading.Thread(target=release.wait, args=(30,),
                             name="selftest-wedge", daemon=True)
        t.start()
        pt.set_flags({"stack_sample_hz": 100.0})
        time.sleep(0.3)
        diag = _stacks.doctor().diagnose("manual", force=True)
        assert diag and diag["culprit"], diag
        body = json.dumps(_fleet.local_snapshot("selftest-host"),
                          default=str).encode()
        req = urllib.request.Request(
            f"http://127.0.0.1:{srv.port}/fleet/push", data=body,
            headers={"Content-Type": "application/json"},
            method="POST")
        with urllib.request.urlopen(req, timeout=10) as r:
            assert r.status == 200
        bundle = collect(f"127.0.0.1:{srv.port}",
                         os.path.join(tmp, "bundle"), fleet=True,
                         quiet=True)
        for fname in ("manifest.json", "stacks.json", "flight.json",
                      "metrics.prom", "stacks_collapsed.txt",
                      os.path.join("fleet", "stacks.json")):
            assert os.path.exists(os.path.join(bundle, fname)), fname
        manifest = _load_json(bundle, "manifest.json")
        assert not manifest["errors"], manifest["errors"]
        assert manifest.get("flags"), "flags missing from manifest"
        import io
        buf = io.StringIO()
        rc = render(bundle, out=buf)
        report = buf.getvalue()
        assert rc == 0
        assert "CULPRIT" in report, report
        assert "selftest-host" in report, report
        assert "selftest_step" in report, report
        release.set()
    finally:
        pt.set_flags({"stack_sample_hz": 0.0})
        srv.stop()
        _metrics.set_enabled(False)
        _fleet.aggregator().reset()
        _stacks.reset()
        _flight.recorder().reset()
        shutil.rmtree(tmp, ignore_errors=True)
    print("self-test OK")
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="collect + render wedge-forensics bundles")
    ap.add_argument("command", nargs="?", default="collect",
                    choices=["collect", "render"])
    ap.add_argument("bundle", nargs="?",
                    help="bundle dir (render mode)")
    ap.add_argument("--url", help="exporter host:port to collect from")
    ap.add_argument("--out", help="bundle output dir "
                                  "(default postmortem-<ts>)")
    ap.add_argument("--fleet", action="store_true",
                    help="also pull the /fleet views incl. the "
                         "/fleet/stacks fan-out")
    ap.add_argument("--tar", action="store_true",
                    help="archive the bundle as .tar.gz")
    ap.add_argument("--self-test", action="store_true")
    args = ap.parse_args(argv)
    if args.self_test:
        return self_test()
    if args.command == "render" and args.bundle and not args.url:
        return render(args.bundle)
    if not args.url:
        ap.error("--url HOST:PORT is required to collect "
                 "(or pass a bundle dir to render)")
    out = args.out or args.bundle \
        or f"postmortem-{time.strftime('%Y%m%d-%H%M%S')}"
    bundle = collect(args.url, out, fleet=args.fleet, tar=args.tar)
    if args.command == "render":
        return render(out)
    return 0


if __name__ == "__main__":
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    sys.exit(main())
