"""Framework-free ResNet-50 train-step floor probe.

Hand-rolled raw-JAX RN50 (no paddle_tpu imports on the model path):
bf16 params/activations, NHWC, fused-form BN (single-pass fp32 stats,
folded scale/shift), SGD+momentum, one donated jit. If THIS gets the
same ~2260 img/s as the framework bench, the wall is the XLA conv path
on this chip, not framework overhead; if it's faster, the delta is our
overhead budget, and its HLO is the template to chase.

Usage: python tools/rn50_floor.py [batch]   (prints one JSON line)
"""

from __future__ import annotations

import functools
import json
import os
import sys
import time

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)

BLOCKS = {50: ((3, 64), (4, 128), (6, 256), (3, 512))}


def _conv(x, w, stride=1):
    import jax.lax as lax
    return lax.conv_general_dilated(
        x, w, (stride, stride), "SAME",
        dimension_numbers=("NHWC", "HWIO", "NHWC"))


def _bn_train(x, gamma, beta):
    """Single-pass batch-norm: fp32 sibling reductions, bf16 apply."""
    import jax.numpy as jnp
    xf = x.astype(jnp.float32)
    mean = jnp.mean(xf, axis=(0, 1, 2))
    mean_sq = jnp.mean(jnp.square(xf), axis=(0, 1, 2))
    var = jnp.maximum(mean_sq - jnp.square(mean), 0.0)
    inv = gamma * (var + 1e-5) ** -0.5
    return (x * inv.astype(x.dtype)
            + (beta - mean * inv).astype(x.dtype))


def init_params(rng):
    import numpy as np
    p = {}

    def conv(name, kh, kw, cin, cout):
        fan = kh * kw * cin
        p[name] = (rng.normal(0, (2.0 / fan) ** 0.5,
                              (kh, kw, cin, cout)).astype("float32"))

    def bn(name, c):
        p[name + "/g"] = np.ones(c, "float32")
        p[name + "/b"] = np.zeros(c, "float32")

    conv("stem", 7, 7, 3, 64)
    bn("stem_bn", 64)
    cin = 64
    for si, (nblocks, width) in enumerate(BLOCKS[50]):
        cout = width * 4
        for bi in range(nblocks):
            pre = f"s{si}b{bi}"
            if bi == 0:
                conv(pre + "/proj", 1, 1, cin, cout)
                bn(pre + "/proj_bn", cout)
            conv(pre + "/c1", 1, 1, cin, width)
            bn(pre + "/bn1", width)
            conv(pre + "/c2", 3, 3, width, width)
            bn(pre + "/bn2", width)
            conv(pre + "/c3", 1, 1, width, cout)
            bn(pre + "/bn3", cout)
            cin = cout
    p["fc/w"] = rng.normal(0, 0.01, (2048, 1000)).astype("float32")
    p["fc/b"] = np.zeros(1000, "float32")
    return p


def forward(params, x):
    import jax
    import jax.numpy as jnp
    import jax.lax as lax
    bf = jnp.bfloat16
    pb = {k: v.astype(bf) if v.ndim == 4 or k == "fc/w" else v
          for k, v in params.items()}
    h = _conv(x, pb["stem"], 2)
    h = jax.nn.relu(_bn_train(h, params["stem_bn/g"],
                              params["stem_bn/b"]))
    h = lax.reduce_window(h, -jnp.inf, lax.max, (1, 3, 3, 1),
                          (1, 2, 2, 1), "SAME")
    cin = 64
    for si, (nblocks, width) in enumerate(BLOCKS[50]):
        cout = width * 4
        for bi in range(nblocks):
            pre = f"s{si}b{bi}"
            stride = 2 if (bi == 0 and si > 0) else 1
            if bi == 0:
                sc = _bn_train(_conv(h, pb[pre + "/proj"], stride),
                               params[pre + "/proj_bn/g"],
                               params[pre + "/proj_bn/b"])
            else:
                sc = h
            y = jax.nn.relu(_bn_train(_conv(h, pb[pre + "/c1"], 1),
                                      params[pre + "/bn1/g"],
                                      params[pre + "/bn1/b"]))
            y = jax.nn.relu(_bn_train(_conv(y, pb[pre + "/c2"], stride),
                                      params[pre + "/bn2/g"],
                                      params[pre + "/bn2/b"]))
            y = _bn_train(_conv(y, pb[pre + "/c3"], 1),
                          params[pre + "/bn3/g"],
                          params[pre + "/bn3/b"])
            h = jax.nn.relu(y + sc)
            cin = cout
    h = jnp.mean(h, axis=(1, 2))
    return h.astype(bf) @ pb["fc/w"] + params["fc/b"]


def main() -> None:
    from bench import _probe_backend, acquire_chip_lock
    acquire_chip_lock("rn50_floor")
    if not _probe_backend():
        print("[floor] backend unreachable; aborting", file=sys.stderr)
        sys.exit(3)
    import numpy as np

    import jax
    import jax.numpy as jnp
    from paddle_tpu.sysconfig import enable_compile_cache
    enable_compile_cache()

    batch = int(sys.argv[1]) if len(sys.argv) > 1 else 128
    rng = np.random.default_rng(0)
    params = init_params(rng)
    vel = {k: np.zeros_like(v) for k, v in params.items()}
    x = jnp.asarray(rng.normal(0, 1, (batch, 224, 224, 3)),
                    jnp.bfloat16)
    labels = jnp.asarray(rng.integers(0, 1000, (batch,)))

    def loss_fn(p, xb, yb):
        logits = forward(p, xb).astype(jnp.float32)
        logp = jax.nn.log_softmax(logits)
        return -jnp.take_along_axis(logp, yb[:, None], 1).mean()

    @functools.partial(jax.jit, donate_argnums=(0, 1))
    def step(p, v, xb, yb):
        loss, g = jax.value_and_grad(loss_fn)(p, xb, yb)
        v = jax.tree.map(lambda vi, gi: 0.9 * vi + gi, v, g)
        p = jax.tree.map(lambda pi, vi: pi - 0.1 * vi, p, v)
        return p, v, loss

    for i in range(4):  # donated-layout fixpoint
        t0 = time.time()
        params, vel, loss = step(params, vel, x, labels)
        print(f"[floor] warmup {i}: {time.time() - t0:.2f}s "
              f"loss={float(loss):.3f}", file=sys.stderr)
    n = 20
    t0 = time.perf_counter()
    for _ in range(n):
        params, vel, loss = step(params, vel, x, labels)
    _ = float(loss)  # tunnel-safe sync (block_until_ready unreliable)
    dt = (time.perf_counter() - t0) / n
    ips = batch / dt
    print(json.dumps({
        "metric": "raw-JAX ResNet-50 floor images/sec/chip",
        "value": round(ips, 1), "unit": "images/sec",
        "ms_per_step": round(dt * 1e3, 2), "batch": batch,
        "vs_baseline": round(ips * 12.3e9 / 1e12 / (0.8 * 197.0), 4),
        "device": str(jax.devices()[0].device_kind)}))


if __name__ == "__main__":
    main()
