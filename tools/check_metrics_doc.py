"""CI check: every metric name registered in code must be documented.

Thin shim over the ``metrics-doc`` ptlint pass
(``paddle_tpu/analysis/metrics_doc.py``) — the AST walk over the
Python factories, the ``pt_mon_add`` regex scan of ``csrc/``, and the
CLI output live there now; this file only preserves the historical
entry point and public API (``collect_metrics`` /
``collect_native_metrics`` / ``main``).  Run
``python tools/ptlint.py --all`` for the full pass registry, or this
script for just the metrics contract.

Usage: python tools/check_metrics_doc.py   (exit 0 ok, 1 violations)
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from ptlint import ANALYSIS  # noqa: E402

_impl = ANALYSIS.metrics_doc

ROOT = _impl.ROOT
PKG_DIR = _impl.PKG_DIR
CSRC_DIR = _impl.CSRC_DIR
DOC = _impl.DOC

collect_metrics = _impl.collect_metrics
collect_native_metrics = _impl.collect_native_metrics
main = _impl.cli_main


if __name__ == "__main__":
    sys.exit(main())
