"""CI check: every metric name registered in code must be documented.

Mirror of ``check_flags_doc.py`` for the metrics registry: walks every
``counter(...)`` / ``gauge(...)`` / ``histogram(...)`` call under
``paddle_tpu/`` by AST (no framework import — milliseconds, no jax) and
fails when a literal metric name does not appear in
``docs/observability.md`` — the canonical metric index scrapers and
dashboards are built from. Dynamically-named instruments (the
user-facing ``obs.counter(my_name)`` API) have non-constant first
arguments and are out of scope by construction; names starting with
``selftest_`` (CLI self-test fixtures) are ignored.

Also covers the NATIVE stat registry: literal ``pt_mon_add("...")``
names in ``csrc/*.cc`` and literal ``stat_add("...")`` names in the
Python tree (both land in the same ``pt_mon`` registry and surface on
the STATS wire reply and the ``pt_native_stat`` bridge) must appear in
``docs/observability.md`` too — C++-side metrics used to be able to
drift undocumented.

Usage: python tools/check_metrics_doc.py   (exit 0 ok, 1 violations)
"""

from __future__ import annotations

import ast
import os
import re
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PKG_DIR = os.path.join(ROOT, "paddle_tpu")
CSRC_DIR = os.path.join(ROOT, "csrc")
DOC = os.path.join(ROOT, "docs", "observability.md")

_FACTORIES = {"counter", "gauge", "histogram"}
# native stat registrations: C++ pt_mon_add / Python native.stat_add
_NATIVE_FACTORIES = {"stat_add"}
_PT_MON_RE = re.compile(r'pt_mon_add\(\s*"([^"]+)"')


def _call_name(node: ast.Call) -> str:
    f = node.func
    if isinstance(f, ast.Name):
        return f.id
    if isinstance(f, ast.Attribute):
        return f.attr
    return ""


def collect_metrics(pkg_dir: str = PKG_DIR):
    """{name: [file:line, ...]} for every literal-named instrument."""
    out = {}
    for dirpath, _, files in os.walk(pkg_dir):
        for fname in files:
            if not fname.endswith(".py"):
                continue
            path = os.path.join(dirpath, fname)
            try:
                tree = ast.parse(open(path).read(), filename=path)
            except SyntaxError as e:  # pragma: no cover
                print(f"check_metrics_doc: cannot parse {path}: {e}",
                      file=sys.stderr)
                return None
            for node in ast.walk(tree):
                if not (isinstance(node, ast.Call)
                        and (_call_name(node) in _FACTORIES
                             or _call_name(node) in _NATIVE_FACTORIES)
                        and node.args
                        and isinstance(node.args[0], ast.Constant)
                        and isinstance(node.args[0].value, str)):
                    continue
                name = node.args[0].value
                if not name or name.startswith("selftest_"):
                    continue
                rel = os.path.relpath(path, ROOT)
                out.setdefault(name, []).append(
                    f"{rel}:{node.lineno}")
    return out


def collect_native_metrics(csrc_dir: str = CSRC_DIR):
    """{name: [file:line, ...]} for every literal pt_mon_add() stat in
    the C++ sources (regex scan — no C++ parser needed for literal
    first arguments; dynamically-built names are out of scope like
    their Python counterparts)."""
    out = {}
    if not os.path.isdir(csrc_dir):
        return out
    for fname in sorted(os.listdir(csrc_dir)):
        if not fname.endswith((".cc", ".c", ".h")):
            continue
        path = os.path.join(csrc_dir, fname)
        try:
            text = open(path).read()
        except OSError:  # pragma: no cover
            continue
        for i, line in enumerate(text.splitlines(), 1):
            for m in _PT_MON_RE.finditer(line):
                rel = os.path.relpath(path, ROOT)
                out.setdefault(m.group(1), []).append(f"{rel}:{i}")
    return out


def main() -> int:
    metrics = collect_metrics()
    if metrics is None:
        return 1
    if not metrics:
        print("check_metrics_doc: no instrument registrations found "
              f"under {PKG_DIR} — parser broken?", file=sys.stderr)
        return 1
    for name, sites in collect_native_metrics().items():
        metrics.setdefault(name, []).extend(sites)
    try:
        doc = open(DOC).read()
    except OSError as e:
        print(f"check_metrics_doc: cannot read {DOC}: {e}",
              file=sys.stderr)
        return 1
    missing = {n: sites for n, sites in metrics.items() if n not in doc}
    for name in sorted(missing):
        print(f"{name}: registered at {', '.join(missing[name])} but "
              "not mentioned in docs/observability.md",
              file=sys.stderr)
    if missing:
        print(f"check_metrics_doc: {len(missing)} undocumented of "
              f"{len(metrics)} metric names", file=sys.stderr)
        return 1
    print(f"check_metrics_doc: OK ({len(metrics)} metric names "
          "documented)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
