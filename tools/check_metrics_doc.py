"""CI check: every metric name registered in code must be documented.

Mirror of ``check_flags_doc.py`` for the metrics registry: walks every
``counter(...)`` / ``gauge(...)`` / ``histogram(...)`` call under
``paddle_tpu/`` by AST (no framework import — milliseconds, no jax) and
fails when a literal metric name does not appear in
``docs/observability.md`` — the canonical metric index scrapers and
dashboards are built from. Dynamically-named instruments (the
user-facing ``obs.counter(my_name)`` API) have non-constant first
arguments and are out of scope by construction; names starting with
``selftest_`` (CLI self-test fixtures) are ignored.

Usage: python tools/check_metrics_doc.py   (exit 0 ok, 1 violations)
"""

from __future__ import annotations

import ast
import os
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PKG_DIR = os.path.join(ROOT, "paddle_tpu")
DOC = os.path.join(ROOT, "docs", "observability.md")

_FACTORIES = {"counter", "gauge", "histogram"}


def _call_name(node: ast.Call) -> str:
    f = node.func
    if isinstance(f, ast.Name):
        return f.id
    if isinstance(f, ast.Attribute):
        return f.attr
    return ""


def collect_metrics(pkg_dir: str = PKG_DIR):
    """{name: [file:line, ...]} for every literal-named instrument."""
    out = {}
    for dirpath, _, files in os.walk(pkg_dir):
        for fname in files:
            if not fname.endswith(".py"):
                continue
            path = os.path.join(dirpath, fname)
            try:
                tree = ast.parse(open(path).read(), filename=path)
            except SyntaxError as e:  # pragma: no cover
                print(f"check_metrics_doc: cannot parse {path}: {e}",
                      file=sys.stderr)
                return None
            for node in ast.walk(tree):
                if not (isinstance(node, ast.Call)
                        and _call_name(node) in _FACTORIES
                        and node.args
                        and isinstance(node.args[0], ast.Constant)
                        and isinstance(node.args[0].value, str)):
                    continue
                name = node.args[0].value
                if not name or name.startswith("selftest_"):
                    continue
                rel = os.path.relpath(path, ROOT)
                out.setdefault(name, []).append(
                    f"{rel}:{node.lineno}")
    return out


def main() -> int:
    metrics = collect_metrics()
    if metrics is None:
        return 1
    if not metrics:
        print("check_metrics_doc: no instrument registrations found "
              f"under {PKG_DIR} — parser broken?", file=sys.stderr)
        return 1
    try:
        doc = open(DOC).read()
    except OSError as e:
        print(f"check_metrics_doc: cannot read {DOC}: {e}",
              file=sys.stderr)
        return 1
    missing = {n: sites for n, sites in metrics.items() if n not in doc}
    for name in sorted(missing):
        print(f"{name}: registered at {', '.join(missing[name])} but "
              "not mentioned in docs/observability.md",
              file=sys.stderr)
    if missing:
        print(f"check_metrics_doc: {len(missing)} undocumented of "
              f"{len(metrics)} metric names", file=sys.stderr)
        return 1
    print(f"check_metrics_doc: OK ({len(metrics)} metric names "
          "documented)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
