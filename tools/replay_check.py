#!/usr/bin/env python
"""Bitwise-exact resume prover (checkpoint v3).

A fault-tolerant trainer is only trustworthy if "resume" means *the
same run*, not *a similar run*. This tool proves it end to end with
real subprocesses:

1. **control** — train to completion, uninterrupted; dump the final
   weights.
2. **victim**  — identical trainer, SIGKILLed mid-epoch by a
   deterministic fault (`train_step:step=7:kill=9`).
3. **resume**  — rerun the victim over the same checkpoint directory;
   it restores the newest intact v3 checkpoint (params, optimizer
   slots, the RNG key stream, GradScaler state) and re-enters the data
   stream at the saved offset.
4. assert the resumed run's final weights are **bitwise identical** to
   the control's.

The trainer deliberately uses a Dropout layer (so the restored RNG
stream is load-bearing), fp16 AMP with a dynamic GradScaler (so the
restored scaler state is load-bearing), and a DataLoader (so the
sampler-offset resume path `DataLoader.iter_from` is exercised, not
the replay fallback).

Usage:
  python tools/replay_check.py --self-test
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import signal
import subprocess
import sys
import tempfile
import textwrap

import numpy as np

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if ROOT not in sys.path:  # runnable from any cwd
    sys.path.insert(0, ROOT)

# Trainer: dropout + fp16 GradScaler + DataLoader, auto-checkpointing
# every 2 steps; dumps final weights (raw arrays) + the resume point.
_TRAINER = textwrap.dedent("""
    import json, os, sys
    import numpy as np
    import jax
    jax.config.update("jax_platforms", "cpu")
    import paddle_tpu as pt
    from paddle_tpu import io
    from paddle_tpu.sysconfig import enable_compile_cache

    enable_compile_cache()
    ckdir, outpath, final_npz = sys.argv[1], sys.argv[2], sys.argv[3]

    rng = np.random.default_rng(0)
    x = rng.normal(size=(96, 4)).astype(np.float32)
    y = rng.integers(0, 2, (96,)).astype(np.int64)
    loader = pt.data.DataLoader(pt.data.TensorDataset(x, y),
                                batch_size=8)   # 12 steps/epoch
    pt.seed(0)
    net = pt.nn.Sequential(pt.nn.Linear(4, 16), pt.nn.ReLU(),
                           pt.nn.Dropout(0.5), pt.nn.Linear(16, 2))
    model = pt.hapi.Model(
        net, loss=lambda o, yy: pt.nn.functional.cross_entropy(o, yy),
        optimizer=pt.optimizer.SGD(learning_rate=0.1))
    resumed = io.AsyncCheckpointer(ckdir).latest_step() or 0
    with open(outpath, "w") as f:
        json.dump({"resumed": resumed}, f)
    model.fit(loader, epochs=1, verbose=0, ckpt_dir=ckdir,
              save_steps=2, amp="float16")
    np.savez(final_npz, **{k: np.asarray(v)
                           for k, v in net.state_dict().items()})
    with open(outpath, "w") as f:
        json.dump({"resumed": resumed, "done": True}, f)
""")


class CheckFailure(AssertionError):
    pass


def _check(cond, msg):
    if not cond:
        raise CheckFailure(msg)


def _env(tmp, fault_spec=None):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = ROOT + os.pathsep + env.get("PYTHONPATH", "")
    env["FLAGS_enable_metrics"] = "1"
    env["FLAGS_metrics_port"] = "-1"
    env["FLAGS_trace_dir"] = os.path.join(tmp, "trace")
    if fault_spec:
        env["FLAGS_fault_spec"] = fault_spec
    else:
        env.pop("FLAGS_fault_spec", None)
    return env


def _run_trainer(tmp, ckdir, tag, fault_spec=None, timeout=240):
    script = os.path.join(tmp, "replay_trainer.py")
    if not os.path.exists(script):
        with open(script, "w") as f:
            f.write(_TRAINER)
    out = os.path.join(tmp, f"result_{tag}.json")
    npz = os.path.join(tmp, f"final_{tag}.npz")
    proc = subprocess.run(
        [sys.executable, script, ckdir, out, npz],
        env=_env(tmp, fault_spec), capture_output=True, text=True,
        timeout=timeout)
    result = json.load(open(out)) if os.path.exists(out) else {}
    return proc, result, npz


def run_check(tmp: str) -> str:
    """The full control / SIGKILL / resume / bitwise-compare cycle.
    Raises :class:`CheckFailure` with a diagnostic on any breach."""
    # 1. control: one uninterrupted run
    ck_a = os.path.join(tmp, "replay_ck_control")
    p, res, npz_a = _run_trainer(tmp, ck_a, "control")
    _check(p.returncode == 0 and res.get("done"),
           f"control run failed rc={p.returncode}\n{p.stderr}")

    # 2. victim: SIGKILL lands mid-epoch at train step 7
    ck_b = os.path.join(tmp, "replay_ck_victim")
    p, res, _ = _run_trainer(tmp, ck_b, "victim",
                             fault_spec="train_step:step=7:kill=9")
    _check(p.returncode == -signal.SIGKILL,
           f"expected SIGKILL death, rc={p.returncode}\n{p.stderr}")

    # 3. resume over the same directory from the newest intact v3 ckpt
    from paddle_tpu import io
    latest = io.AsyncCheckpointer(ck_b).latest_step()
    _check(latest and 0 < latest < 12,
           f"expected a mid-epoch checkpoint, got {latest}")
    host = io.AsyncCheckpointer(ck_b).host_state()
    _check(host and host.get("global_step") == latest,
           f"v3 host_state missing/stale: {host}")
    p, res, npz_b = _run_trainer(tmp, ck_b, "resume")
    _check(p.returncode == 0 and res.get("done"),
           f"resume run failed rc={p.returncode}\n{p.stderr}")
    _check(res.get("resumed") == latest,
           f"resume started at {res.get('resumed')}, wanted {latest}")

    # 4. bitwise comparison of the final weights
    a, b = np.load(npz_a), np.load(npz_b)
    _check(sorted(a.files) == sorted(b.files),
           f"weight sets differ: {a.files} vs {b.files}")
    diffs = [k for k in a.files
             if a[k].tobytes() != b[k].tobytes()]
    if diffs:
        worst = max(float(np.abs(a[k].astype(np.float64)
                                 - b[k].astype(np.float64)).max())
                    for k in diffs)
        raise CheckFailure(
            "resumed weights are NOT bitwise-identical to the "
            f"control run: {diffs} (max abs diff {worst:.3e})")
    return (f"SIGKILL at step 7, resumed from intact ckpt-{latest} "
            f"(host_state offset {host.get('batch_in_epoch')}), "
            f"{len(a.files)} weight arrays bitwise-equal to control")


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--self-test", action="store_true",
                        help="run the full check on CPU and report")
    parser.add_argument("--keep", action="store_true",
                        help="keep the scratch directory")
    args = parser.parse_args(argv)
    if not args.self_test:
        parser.error("pass --self-test")

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax
    jax.config.update("jax_platforms", "cpu")

    tmp = tempfile.mkdtemp(prefix="replay_check_")
    try:
        summary = run_check(tmp)
        print(f"[replay] exact_resume: OK — {summary}")
    except CheckFailure as e:
        print(f"[replay] exact_resume: FAIL — {e}", file=sys.stderr)
        return 1
    finally:
        if args.keep:
            print(f"[replay] scratch kept at {tmp}")
        else:
            shutil.rmtree(tmp, ignore_errors=True)
    print("replay check self-test OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
