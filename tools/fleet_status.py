"""Fleet status CLI: render one live table for a multi-host job.

Reads the rank-0 aggregator's federation endpoints
(observability/fleet.py — ``/fleet?format=json``, ``/fleet/goodput``,
``/fleet/health``) and prints the per-host table an operator would
otherwise assemble by ssh-ing N hosts: push freshness, self-reported
health, exporter port, goodput headline, worst badput bucket, and
straggler events.

Usage:
    python tools/fleet_status.py HOST:PORT            # rank-0 exporter
    python tools/fleet_status.py HOST:PORT --stacks   # + live top frame
    python tools/fleet_status.py --self-test          # no-TPU CI drill

``--stacks`` adds each worker's *current top frame* beside its health
row, pulled live through the aggregator's ``/fleet/stacks`` fan-out
(observability/stacks.py): the most-blocked thread per worker wins
(blocked_on_lock / blocked_in_collective / blocked_in_io before
running), so a wedged worker's row shows the wedged frame itself.

``--self-test`` boots a real 3-process mini-fleet against an in-process
aggregator and asserts the federation contract end to end: merged
counters equal the per-host sum, gauges carry ``{host=}`` labels,
histograms merge bucket-wise, and a SIGKILLed worker flips
``/fleet/health`` to 503 (stale) without breaking the merged view.
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import subprocess
import sys
import time
import urllib.error
import urllib.request
from typing import Any, Dict, Optional, Tuple

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if ROOT not in sys.path:
    sys.path.insert(0, ROOT)


def _get(addr: str, path: str, timeout_s: float = 5.0
         ) -> Tuple[int, Any]:
    """GET http://addr/path; returns (status, parsed-JSON-or-text).
    Error statuses (e.g. /fleet/health 503) are returned, not raised."""
    try:
        with urllib.request.urlopen(f"http://{addr}{path}",
                                    timeout=timeout_s) as r:
            body = r.read().decode()
            status = r.status
    except urllib.error.HTTPError as e:
        body = e.read().decode()
        status = e.code
    try:
        return status, json.loads(body)
    except ValueError:
        return status, body


def _fleet_ttft_p99_ms(view: Dict[str, Any]) -> Optional[float]:
    """Fleet-wide p99 TTFT from the merged serving_ttft_ms histogram
    (the shared bucket estimator in observability/metrics.py)."""
    ent = (view.get("metrics") or {}).get("serving_ttft_ms") or {}
    merged: Dict[str, float] = {}
    for s in ent.get("series", []):
        for le, c in (s.get("buckets") or {}).items():
            merged[le] = merged.get(le, 0.0) + float(c)
    if not merged or merged.get("+Inf", 0) <= 0:
        return None
    from paddle_tpu.observability.metrics import quantile_from_buckets
    return quantile_from_buckets(merged, 0.99)


def _host_alert_states(alerts: Dict[str, Any]) -> Dict[str, str]:
    """host -> its worst alert state across every SLO."""
    order = ("inactive", "resolved", "pending", "firing")
    worst: Dict[str, str] = {}
    for ent in (alerts.get("slos") or {}).values():
        for host, ha in (ent.get("hosts") or {}).items():
            st = ha.get("state", "inactive")
            if st not in order:
                continue
            cur = worst.get(host, "inactive")
            if order.index(st) > order.index(cur):
                worst[host] = st
            else:
                worst.setdefault(host, cur)
    return worst


def _host_top_frames(fstacks: Dict[str, Any]) -> Dict[str, str]:
    """host -> one-line current-frame summary from the /fleet/stacks
    fan-out: the most-blocked thread wins (a wedge beats an idle
    accept loop); unreachable workers show the dial error."""
    out: Dict[str, str] = {}
    for host, rec in (fstacks.get("hosts") or {}).items():
        if rec.get("error"):
            out[host] = f"unreachable: {rec['error']}"[:60]
            continue
        threads = (rec.get("stacks") or {}).get("threads", [])
        if not threads:
            out[host] = "-"
            continue
        blocked = [t for t in threads
                   if t.get("state", "running") != "running"]
        pick = blocked[0] if blocked else next(
            (t for t in threads if t.get("name") == "MainThread"),
            threads[0])
        frame = pick.get("frame") or pick.get("top") or "?"
        out[host] = f"{pick.get('name')}:{pick.get('state')} {frame}"
    return out


def _render_router(addr: str) -> None:
    """The ``--router`` backend-pool table: every live front-door
    router registered on the exporter's ``GET /router`` endpoint
    (serving_llm/router.py), one row per backend with its rotation
    state, live stream count, and breaker posture."""
    code, rt = _get(addr, "/router")
    routers = rt.get("routers", []) if isinstance(rt, dict) else []
    if code != 200 or not routers:
        print("router: none registered on this exporter")
        return
    for r in routers:
        print(f"router @ {r.get('addr')}: "
              f"{r.get('available', 0)}/{len(r.get('backends', []))} "
              f"backend(s) in rotation, "
              f"streams={r.get('streams_active', 0)} "
              f"failovers={r.get('failovers_total', 0)} "
              f"retries={r.get('retries_total', 0)} "
              f"shed={r.get('shed_total', 0)}")
        cols = ("backend", "state", "streams", "breaker",
                "consec fails", "opened", "last error")
        rows = []
        for b in r.get("backends", []):
            br = b.get("breaker") or {}
            rows.append((str(b.get("name")), str(b.get("state")),
                         str(b.get("streams_active", 0)),
                         str(br.get("state", "-")),
                         str(br.get("failures", 0)),
                         str(br.get("opened_total", 0)),
                         str(b.get("last_error") or "-")[:40]))
        widths = [max(len(c), *(len(row[i]) for row in rows)) if rows
                  else len(c) for i, c in enumerate(cols)]
        print("  " + "  ".join(c.ljust(w)
                               for c, w in zip(cols, widths)))
        for row in rows:
            print("  " + "  ".join(v.ljust(w)
                                   for v, w in zip(row, widths)))


def _label_sums(view: Dict[str, Any], name: str,
                label: str) -> Dict[str, float]:
    """label value -> summed series value for one merged metric
    (series without the label are skipped; extra labels like kind=
    are summed over)."""
    ent = (view.get("metrics") or {}).get(name) or {}
    out: Dict[str, float] = {}
    for s in ent.get("series", []):
        v = (s.get("labels") or {}).get(label)
        if v is not None:
            out[str(v)] = out.get(str(v), 0.0) + float(s["value"])
    return out


def _render_tenants(view: Dict[str, Any]) -> None:
    """The ``--tenants`` traffic table: per-tenant admitted/active/
    rejected/shed totals from the merged fleet metrics. Tenant labels
    are the bounded ones from serving_llm/tenancy.py (verbatim up to
    FLAGS_tenant_label_max, overflow-NN buckets beyond)."""
    admitted = _label_sums(view, "llm_tenant_admitted_total", "tenant")
    active = _label_sums(view, "llm_tenant_active", "tenant")
    rejected = _label_sums(view, "llm_admission_rejected_total",
                           "tenant")
    shed = _label_sums(view, "requests_shed_total", "tenant")
    door = _label_sums(view, "router_shed_total", "tenant")
    tenants = sorted(set(admitted) | set(active) | set(rejected)
                     | set(shed) | set(door))
    if not tenants:
        print("tenants: no tenant-labeled serving traffic yet")
        return
    print(f"tenants: {len(tenants)} label(s) across the fleet")
    cols = ("tenant", "admitted", "active", "rejected", "shed",
            "door shed")
    rows = [(t,
             f"{admitted.get(t, 0.0):.0f}",
             f"{active.get(t, 0.0):.0f}",
             f"{rejected.get(t, 0.0):.0f}",
             f"{shed.get(t, 0.0):.0f}",
             f"{door.get(t, 0.0):.0f}") for t in tenants]
    widths = [max(len(c), *(len(r[i]) for r in rows))
              for i, c in enumerate(cols)]
    print("  " + "  ".join(c.ljust(w) for c, w in zip(cols, widths)))
    for r in rows:
        print("  " + "  ".join(v.ljust(w)
                               for v, w in zip(r, widths)))


def render(addr: str, stacks: bool = False, router: bool = False,
           tenants: bool = False) -> int:
    """Print the fleet table; exit 0 healthy, 1 degraded/unreachable."""
    try:
        _, view = _get(addr, "/fleet?format=json")
        hcode, health = _get(addr, "/fleet/health")
        _, gp = _get(addr, "/fleet/goodput")
        _, alerts = _get(addr, "/fleet/alerts")
        fstacks: Dict[str, Any] = {}
        if stacks:
            _, fstacks = _get(addr, "/fleet/stacks")
    except OSError as e:
        print(f"fleet_status: aggregator {addr} unreachable: {e}",
              file=sys.stderr)
        return 1
    hosts = sorted(set(view.get("hosts", {}))
                   | set(health.get("hosts", {})))
    p99 = _fleet_ttft_p99_ms(view)
    alerts = alerts if isinstance(alerts, dict) else {}
    print(f"fleet @ {addr}: {len(hosts)} host(s), "
          f"health={'OK' if hcode == 200 else 'STALE (503)'}, "
          f"fleet goodput {gp.get('goodput_ratio', 0.0):.1%} over "
          f"{gp.get('wall_seconds', 0.0):.1f}s wall, "
          f"TTFT p99 {'-' if p99 is None else f'{p99:.1f}ms'}, "
          f"alerts={alerts.get('worst_state', 'inactive')}")
    host_alerts = _host_alert_states(alerts)
    cols = ("host", "age_s", "stale", "healthy", "port", "goodput",
            "worst badput", "stragglers", "alerts")
    top_frames = _host_top_frames(fstacks) if stacks else {}
    if stacks:
        cols = cols + ("top frame",)
    rows = []
    for h in hosts:
        hh = health.get("hosts", {}).get(h, {})
        gh = gp.get("hosts", {}).get(h, {})
        row = (h,
               f"{hh.get('age_s', float('nan')):.1f}",
               "STALE" if hh.get("stale") else "fresh",
               "yes" if hh.get("healthy") else "NO",
               str(hh.get("port") or "-"),
               f"{gh.get('goodput_ratio', 0.0):.1%}",
               str(gh.get("worst_badput_bucket") or "-"),
               f"{gh.get('straggler_events', 0):.0f}",
               host_alerts.get(h, "inactive"))
        if stacks:
            row = row + (top_frames.get(h, "-"),)
        rows.append(row)
    widths = [max(len(c), *(len(r[i]) for r in rows)) if rows
              else len(c) for i, c in enumerate(cols)]
    print("  ".join(c.ljust(w) for c, w in zip(cols, widths)))
    for r in rows:
        print("  ".join(v.ljust(w) for v, w in zip(r, widths)))
    if router:
        _render_router(addr)
    if tenants:
        _render_tenants(view)
    if view.get("merge_error"):
        print(f"MERGE ERROR: {view['merge_error']}", file=sys.stderr)
        return 1
    return 0 if hcode == 200 else 1


# ------------------------------------------------------------- self-test

_WORKER_SRC = r"""
import os, sys, time
sys.path.insert(0, os.environ["PT_SELFTEST_ROOT"])
import paddle_tpu as pt
from paddle_tpu import observability as obs
from paddle_tpu.observability import server as obs_server
from paddle_tpu.observability import fleet, goodput

rank = int(os.environ["PT_SELFTEST_RANK"])
pt.set_flags({"enable_metrics": True, "fleet_push_interval_s": 0.15})
# own exporter on an ephemeral port: the report-back half of discovery
# (the chosen port rides every pushed snapshot)
obs_server.start(0)
obs.counter("fleet_selftest_total").inc(rank + 1)
obs.counter("fleet_selftest_total").inc(10, route="labeled")
# per-tenant serving traffic for the --tenants table: every worker
# admits for "acme", rank 0 also sheds one "bulkco" request
obs.counter("llm_tenant_admitted_total").inc(rank + 1, tenant="acme")
obs.gauge("llm_tenant_active").set(1.0, tenant="acme")
if rank == 0:
    obs.counter("llm_admission_rejected_total").inc(tenant="bulkco")
obs.gauge("fleet_selftest_gauge").set(float(rank))
obs.histogram("fleet_selftest_ms",
              buckets=obs.metrics.LATENCY_MS_BUCKETS
              ).observe(1.0 * (rank + 1))
obs.histogram("serving_ttft_ms",
              "time to first token: request ingress to first streamed "
              "chunk",
              buckets=obs.metrics.LATENCY_MS_BUCKETS
              ).observe(40.0 * (rank + 1))
led = goodput.ledger()
led.start()
led.attribute("step_compute", 2.0 + rank)
led.attribute("data_wait", 1.0)
fleet.start_reporter(os.environ["PT_FLEET_AGGREGATOR"],
                     host_id=os.environ["PT_FLEET_HOST"])
print("worker %d up" % rank, flush=True)
while True:
    time.sleep(0.1)
"""


def _poll(fn, timeout_s: float, what: str, interval_s: float = 0.25):
    """Poll fn() until it returns a truthy value; raise on timeout."""
    deadline = time.time() + timeout_s
    last = None
    while time.time() < deadline:
        try:
            last = fn()
            if last:
                return last
        except (OSError, ValueError, KeyError):
            pass
        time.sleep(interval_s)
    raise AssertionError(f"self-test: timed out waiting for {what} "
                         f"(last={last!r})")


def _counter_total(view: Dict[str, Any], name: str, **labels) -> float:
    ent = (view.get("metrics") or {}).get(name) or {}
    want = {k: str(v) for k, v in labels.items()}
    total = 0.0
    for s in ent.get("series", []):
        if {k: str(v) for k, v in s["labels"].items()} == want:
            total += float(s["value"])
    return total


def self_test() -> int:
    """3-process federation drill (no TPU, CPU jax): counters sum,
    gauges get host labels, histograms merge exactly, SIGKILL of one
    worker flips /fleet/health stale without breaking /fleet."""
    import paddle_tpu as pt
    from paddle_tpu.observability import server as obs_server

    pt.set_flags({"enable_metrics": True, "fleet_stale_after_s": 2.0})
    srv = obs_server.start(0)
    addr = f"127.0.0.1:{srv.port}"
    workers = []
    try:
        for rank in range(3):
            env = dict(os.environ)
            env.update({"PT_SELFTEST_ROOT": ROOT,
                        "PT_SELFTEST_RANK": str(rank),
                        "PT_FLEET_AGGREGATOR": addr,
                        "PT_FLEET_HOST": f"w{rank}",
                        "JAX_PLATFORMS": "cpu"})
            workers.append(subprocess.Popen(
                [sys.executable, "-c", _WORKER_SRC], env=env))

        def fleet_ready():
            code, v = _get(addr, "/fleet?format=json")
            if code != 200 or v.get("n_hosts", 0) < 3:
                return None
            # unlabeled series summed: 1 + 2 + 3
            if _counter_total(v, "fleet_selftest_total") != 6.0:
                return None
            return v

        view = _poll(fleet_ready, 90, "3 hosts with summed counters")
        # labeled counter series also summed per label set: 3 x 10
        assert _counter_total(view, "fleet_selftest_total",
                              route="labeled") == 30.0, view
        # gauges: one series per host, labeled {host=}
        gauges = {s["labels"]["host"]: s["value"]
                  for s in view["metrics"]["fleet_selftest_gauge"]
                  ["series"]}
        assert gauges == {"w0": 0.0, "w1": 1.0, "w2": 2.0}, gauges
        # histogram merged bucket-wise across identical boundaries
        hist = view["metrics"]["fleet_selftest_ms"]["series"][0]
        assert hist["count"] == 3 and hist["sum"] == 6.0, hist
        assert hist["buckets"]["2.5"] == 2, hist["buckets"]
        # the same numbers on the Prometheus rendering of /fleet
        code, prom = _get(addr, "/fleet")
        assert code == 200 and "fleet_selftest_total 6" in prom, prom
        assert 'fleet_selftest_gauge{host="w1"} 1' in prom, prom
        # health: every worker fresh, each reporting its exporter port
        code, health = _get(addr, "/fleet/health")
        assert code == 200, health
        assert all(not h["stale"] and h["port"]
                   for h in health["hosts"].values()), health
        # goodput roll-up with per-host badput attribution
        code, gp = _get(addr, "/fleet/goodput")
        assert set(gp["hosts"]) == {"w0", "w1", "w2"}, gp
        assert gp["buckets"]["step_compute"] == 9.0, gp["buckets"]
        assert gp["goodput_ratio"] > 0, gp
        assert gp["hosts"]["w0"]["worst_badput_bucket"] == \
            "data_wait", gp["hosts"]["w0"]
        # fleet TTFT p99 via the shared bucket estimator: observations
        # 40/80/120ms all land in finite buckets, so the interpolated
        # p99 sits inside the top straddled bucket (100, 250]
        p99 = _fleet_ttft_p99_ms(view)
        assert p99 is not None and 100.0 < p99 <= 250.0, p99
        # the merged alerts plane answers (no specs registered on the
        # workers, so the fleet verdict is a quiet inactive)
        code, alerts = _get(addr, "/fleet/alerts")
        assert code == 200 and alerts["worst_state"] == "inactive", \
            alerts
        print(f"fleet up: 3 hosts, merged counters/gauges/histograms "
              f"OK @ {addr}")

        # SIGKILL one worker: /fleet/health must flip stale for it
        # while the merged /fleet view keeps serving its last snapshot
        workers[1].kill()
        workers[1].wait(10)

        def w1_stale():
            code, h = _get(addr, "/fleet/health")
            if code != 503:
                return None
            hosts = h["hosts"]
            if not hosts["w1"]["stale"]:
                return None
            assert not hosts["w0"]["stale"], hosts
            assert not hosts["w2"]["stale"], hosts
            return h

        _poll(w1_stale, 30, "w1 stale after SIGKILL")
        code, view = _get(addr, "/fleet?format=json")
        assert code == 200, view
        assert _counter_total(view, "fleet_selftest_total") == 6.0, \
            "merged view broke after a host died"
        assert "merge_error" not in view, view.get("merge_error")
        print("w1 SIGKILLed: /fleet/health 503 (w1 stale), merged "
              "/fleet intact")
        # --stacks column: live workers answer the /fleet/stacks
        # dial-back with real thread dumps; the dead one degrades to
        # a per-host error instead of poisoning the table
        code, fstk = _get(addr, "/fleet/stacks")
        assert code == 200, fstk
        for live in ("w0", "w2"):
            rec = fstk["hosts"][live]
            assert rec.get("error") is None, (live, rec)
            names = [t["name"] for t in rec["stacks"]["threads"]]
            assert "MainThread" in names, (live, names)
        assert fstk["hosts"]["w1"].get("error"), fstk["hosts"]["w1"]
        tops = _host_top_frames(fstk)
        assert tops["w0"] and tops["w0"] != "-", tops
        assert tops["w1"].startswith("unreachable"), tops
        print("/fleet/stacks: live workers dumped, dead worker "
              "degraded to error")
        # --router table: no router lives in the aggregator process,
        # so GET /router answers the empty roster and the renderer
        # degrades to a one-liner instead of erroring
        code, rt = _get(addr, "/router")
        assert code == 200 and rt["routers"] == [], rt
        # --tenants table: the merged view sums the per-tenant series
        # across hosts (1+2+3 admitted for acme, one bulkco reject)
        _, view = _get(addr, "/fleet?format=json")
        adm = _label_sums(view, "llm_tenant_admitted_total", "tenant")
        assert adm.get("acme") == 6.0, adm
        rej = _label_sums(view, "llm_admission_rejected_total",
                          "tenant")
        assert rej.get("bulkco") == 1.0, rej
        print("--tenants: per-tenant series merged across hosts")
        render(addr, stacks=True, router=True, tenants=True)
    finally:
        for p in workers:
            if p.poll() is None:
                p.send_signal(signal.SIGKILL)
        for p in workers:
            try:
                p.wait(10)
            except subprocess.TimeoutExpired:
                pass
        obs_server.stop()
    print("self-test OK")
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="render the fleet-federation status table from a "
                    "rank-0 observability exporter")
    ap.add_argument("aggregator", nargs="?",
                    help="rank-0 exporter address, host:port")
    ap.add_argument("--watch", type=float, metavar="S", default=0,
                    help="re-render every S seconds")
    ap.add_argument("--stacks", action="store_true",
                    help="add each worker's current top frame "
                         "(live /fleet/stacks fan-out)")
    ap.add_argument("--router", action="store_true",
                    help="add the front-door router backend-pool "
                         "table (the exporter's GET /router snapshot)")
    ap.add_argument("--tenants", action="store_true",
                    help="add the per-tenant serving traffic table "
                         "(admitted/active/rejected/shed from the "
                         "merged fleet metrics)")
    ap.add_argument("--self-test", action="store_true")
    args = ap.parse_args(argv)
    if args.self_test:
        return self_test()
    if not args.aggregator:
        ap.error("aggregator address required (or --self-test)")
    addr = args.aggregator.split("//", 1)[-1].rstrip("/")
    if args.watch > 0:
        try:
            while True:
                print("\033[2J\033[H", end="")
                render(addr, stacks=args.stacks, router=args.router,
                       tenants=args.tenants)
                time.sleep(args.watch)
        except KeyboardInterrupt:
            return 0
    return render(addr, stacks=args.stacks, router=args.router,
                  tenants=args.tenants)


if __name__ == "__main__":
    sys.exit(main())
