"""One-shot measurement campaign for when the accelerator is up.

Runs, in order of value per chip-minute (each stage independently
time-capped so a mid-campaign tunnel drop still leaves artifacts):
  1. verification       -> VERIFY_TPU.json  (compiled kernels + parity)
  2. pinned BERT        -> CAPTURE_bert_fused_b32.json   (best-guess cfg)
  3. pinned ResNet      -> CAPTURE_resnet_nhwc_b128.json (best-guess cfg)
  4. comparison configs -> per-leaf BERT, NCHW ResNet
  5. flash sweep        -> CAPTURE_flash.json

Pinned stages (PT_BENCH_* env) keep each subprocess to ONE compile+time
cycle, so a tunnel drop mid-campaign costs one bounded stage instead of
a 50-minute autotune (round-3 lesson: the unpinned bert stage timed out
at 3000s and, because partial output was discarded, left nothing).
Timeouts now preserve the stage's partial stdout/stderr — the per-config
ms/step lines bench.py logs as it goes survive a mid-stage hang.

Usage: python tools/capture_all.py [stage ...]   (default: DEFAULT_PLAN)
Each stage is a subprocess of bench.py so a wedged PJRT init or OOM
kills only that stage; stdout JSON lines are parsed and collected into
CAPTURE_SUMMARY.json.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# name -> (bench.py argv, extra env, budget seconds)
_SKIP = {"PT_BENCH_SKIP_VALIDATE": "1"}  # verify stage covers kernels
STAGES = {
    "verify": (["verify"], {}, 1200),
    "bert_fused_b32": ([], {**_SKIP, "PT_BENCH_BERT_BATCH": "32",
                            "PT_BENCH_FUSED": "1"}, 1800),
    "resnet_nhwc_b128": (["resnet50"],
                         {**_SKIP, "PT_BENCH_RESNET_BATCH": "128",
                          "PT_BENCH_LAYOUT": "NHWC",
                          "PT_BENCH_FUSED": "1"}, 1800),
    "bert_perleaf_b32": ([], {**_SKIP, "PT_BENCH_BERT_BATCH": "32",
                              "PT_BENCH_FUSED": "0"}, 1200),
    "resnet_nchw_b128": (["resnet50"],
                         {**_SKIP, "PT_BENCH_RESNET_BATCH": "128",
                          "PT_BENCH_LAYOUT": "NCHW",
                          "PT_BENCH_FUSED": "1"}, 1200),
    "flash": (["flash"], _SKIP, 1800),
    # unpinned autotunes (the driver's default bench path)
    "bert": ([], {}, 3000),
    "resnet": (["resnet50"], {}, 3000),
}
DEFAULT_PLAN = ["verify", "bert_fused_b32", "resnet_nhwc_b128",
                "bert_perleaf_b32", "resnet_nchw_b128", "flash"]


def log(msg: str) -> None:
    print(f"[capture] {msg}", file=sys.stderr, flush=True)


def _text(v) -> str:
    if v is None:
        return ""
    if isinstance(v, bytes):
        return v.decode("utf-8", "replace")
    return v


def run_stage(name: str) -> dict:
    args, env, budget = STAGES[name]
    t0 = time.time()
    log(f"stage {name}: starting (budget {budget}s)")
    stdout, stderr, rc, timed_out = "", "", None, False
    try:
        r = subprocess.run(
            [sys.executable, os.path.join(ROOT, "bench.py"), *args],
            capture_output=True, text=True, timeout=budget, cwd=ROOT,
            env={**os.environ, **env})
        stdout, stderr, rc = r.stdout, r.stderr, r.returncode
    except subprocess.TimeoutExpired as e:
        # partial output is the whole point: bench.py logs each
        # config's ms/step to stderr as it measures
        stdout, stderr = _text(e.stdout), _text(e.stderr)
        timed_out = True
        log(f"stage {name}: TIMED OUT after {budget}s "
            f"(keeping partial output)")
    parsed = None
    for line in (stdout or "").splitlines():
        line = line.strip()
        if line.startswith("{"):
            try:
                parsed = json.loads(line)
            except json.JSONDecodeError:
                continue
    # a stage that printed its result JSON and then wedged in PJRT
    # teardown still produced a usable measurement — don't re-run it
    out = {"stage": name,
           "ok": parsed is not None and (rc == 0 or timed_out),
           "rc": rc, "timed_out": timed_out, "parsed": parsed,
           "elapsed_s": round(time.time() - t0, 1),
           "env": env,
           "stderr_tail": (stderr or "").splitlines()[-25:]}
    result_path = os.path.join(ROOT, f"CAPTURE_{name}.json")
    with open(result_path, "w") as f:
        json.dump(out, f, indent=1)
    log(f"stage {name}: rc={rc} parsed={parsed} "
        f"({out['elapsed_s']}s) -> {result_path}")
    return out


def main() -> None:
    wanted = sys.argv[1:] or DEFAULT_PLAN
    unknown = [w for w in wanted if w not in STAGES]
    if unknown:
        raise SystemExit(f"unknown stages {unknown}; pick from "
                         f"{sorted(STAGES)}")
    results = [run_stage(name) for name in wanted]
    # merge into any existing summary so a retry campaign over the
    # remaining stages doesn't erase earlier stages' records
    summary_path = os.path.join(ROOT, "CAPTURE_SUMMARY.json")
    by_stage: dict = {}
    try:
        with open(summary_path) as f:
            for r in json.load(f).get("results", []):
                by_stage[r.get("stage")] = r
    except (OSError, json.JSONDecodeError):
        pass
    for r in results:
        by_stage[r["stage"]] = r
    summary = {"when": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
               "results": list(by_stage.values())}
    with open(summary_path, "w") as f:
        json.dump(summary, f, indent=1)
    log(f"campaign done: {[(r['stage'], r['ok']) for r in results]}")
    sys.exit(0 if all(r["ok"] for r in results) else 1)


if __name__ == "__main__":
    main()
