"""One-shot measurement campaign for when the accelerator is up.

Runs, in order of value per chip-minute (each stage independently
time-capped so a mid-campaign tunnel drop still leaves artifacts):
  1. verification  -> VERIFY_TPU.json  (compiled kernels + train parity)
  2. BERT bench    -> CAPTURE_bert.json
  3. ResNet bench  -> CAPTURE_resnet.json
  4. flash sweep   -> CAPTURE_flash.json

Usage: python tools/capture_all.py [stage ...]   (default: all)
Each stage is a subprocess of bench.py so a wedged PJRT init or OOM
kills only that stage; stdout JSON lines are parsed and collected into
CAPTURE_SUMMARY.json.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

STAGES = {
    "verify": (["verify"], 1200),
    "bert": ([], 3000),
    "resnet": (["resnet50"], 3000),
    "flash": (["flash"], 1800),
}


def log(msg: str) -> None:
    print(f"[capture] {msg}", file=sys.stderr, flush=True)


def run_stage(name: str) -> dict:
    args, budget = STAGES[name]
    t0 = time.time()
    log(f"stage {name}: starting (budget {budget}s)")
    try:
        r = subprocess.run(
            [sys.executable, os.path.join(ROOT, "bench.py"), *args],
            capture_output=True, text=True, timeout=budget, cwd=ROOT)
    except subprocess.TimeoutExpired:
        log(f"stage {name}: TIMED OUT after {budget}s")
        return {"stage": name, "ok": False, "error": f"timeout {budget}s"}
    parsed = None
    for line in (r.stdout or "").splitlines():
        line = line.strip()
        if line.startswith("{"):
            try:
                parsed = json.loads(line)
            except json.JSONDecodeError:
                continue
    out = {"stage": name, "ok": r.returncode == 0 and parsed is not None,
           "rc": r.returncode, "parsed": parsed,
           "elapsed_s": round(time.time() - t0, 1),
           "stderr_tail": (r.stderr or "").splitlines()[-8:]}
    result_path = os.path.join(ROOT, f"CAPTURE_{name}.json")
    with open(result_path, "w") as f:
        json.dump(out, f, indent=1)
    log(f"stage {name}: rc={r.returncode} parsed={parsed} "
        f"({out['elapsed_s']}s) -> {result_path}")
    return out


def main() -> None:
    wanted = sys.argv[1:] or ["verify", "bert", "resnet", "flash"]
    unknown = [w for w in wanted if w not in STAGES]
    if unknown:
        raise SystemExit(f"unknown stages {unknown}; pick from "
                         f"{sorted(STAGES)}")
    results = [run_stage(name) for name in wanted]
    summary = {"when": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
               "results": results}
    with open(os.path.join(ROOT, "CAPTURE_SUMMARY.json"), "w") as f:
        json.dump(summary, f, indent=1)
    log(f"campaign done: {[(r['stage'], r['ok']) for r in results]}")
    sys.exit(0 if all(r["ok"] for r in results) else 1)


if __name__ == "__main__":
    main()
