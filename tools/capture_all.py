"""One-shot measurement campaign for when the accelerator is up.

Runs, in order of value per chip-minute (each stage independently
time-capped so a mid-campaign tunnel drop still leaves artifacts):
  1. verification       -> VERIFY_TPU.json  (compiled kernels + parity)
  2. pinned BERT        -> CAPTURE_bert_fused_b32.json   (best-guess cfg)
  3. pinned ResNet      -> CAPTURE_resnet_nhwc_b128.json (best-guess cfg)
  4. comparison configs -> per-leaf BERT, NCHW ResNet
  5. flash sweep        -> CAPTURE_flash.json

Pinned stages (PT_BENCH_* env) keep each subprocess to ONE compile+time
cycle, so a tunnel drop mid-campaign costs one bounded stage instead of
a 50-minute autotune (round-3 lesson: the unpinned bert stage timed out
at 3000s and, because partial output was discarded, left nothing).
Timeouts now preserve the stage's partial stdout/stderr — the per-config
ms/step lines bench.py logs as it goes survive a mid-stage hang.

Usage: python tools/capture_all.py [stage ...]   (default: DEFAULT_PLAN)
Each stage is a subprocess of bench.py so a wedged PJRT init or OOM
kills only that stage; stdout JSON lines are parsed and collected into
CAPTURE_SUMMARY.json.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# name -> (argv after bench.py, extra env, budget seconds[, script])
# script (default bench.py) lets a stage run a different tool — the
# profiler stages drive tools/profile_step.py.
_SKIP = {"PT_BENCH_SKIP_VALIDATE": "1"}  # verify stage covers kernels
_SPL1 = {"PT_BENCH_STEPS_PER_LOOP": "1"}  # measured ~1.0x; skip re-timing


def _bert(batch, fused, qkv):
    # The flash train gate is pinned OFF (raised above seq 512) so these
    # stages stay the XLA-attention baseline their historical artifacts
    # were captured as — the flag's default moved to 512 after the
    # in-model bert_b8_flash512 win, and an unpinned re-capture would
    # silently change what every A/B pair compares against. Flash-on
    # stages pin 512 explicitly.
    return ([], {**_SKIP, **_SPL1, "PT_BENCH_BERT_BATCH": str(batch),
                 "PT_BENCH_FUSED": fused,
                 "FLAGS_flash_attention_min_seq_train": "1024",
                 "FLAGS_fused_qkv_projection": qkv}, 900)


# Historical-default pins for the legacy stages below: their artifacts
# were captured with XLA attention (train gate above seq 512) and
# two-pass BN, and both defaults have since flipped — re-captures must
# not silently change configuration under the same artifact name.
_HIST = {"FLAGS_flash_attention_min_seq_train": "1024",
         "FLAGS_batch_norm_single_pass": "0"}

STAGES = {
    "verify": (["verify"], {}, 1200),
    "bert_fused_b32": ([], {**_SKIP, **_HIST,
                            "PT_BENCH_BERT_BATCH": "32",
                            "PT_BENCH_FUSED": "1"}, 1800),
    "resnet_nhwc_b128": (["resnet50"],
                         {**_SKIP, **_HIST,
                          "PT_BENCH_RESNET_BATCH": "128",
                          "PT_BENCH_LAYOUT": "NHWC",
                          "PT_BENCH_FUSED": "1"}, 1800),
    "bert_perleaf_b32": ([], {**_SKIP, **_HIST,
                              "PT_BENCH_BERT_BATCH": "32",
                              "PT_BENCH_FUSED": "0"}, 1200),
    "resnet_nchw_b128": (["resnet50"],
                         {**_SKIP, **_HIST,
                          "PT_BENCH_RESNET_BATCH": "128",
                          "PT_BENCH_LAYOUT": "NCHW",
                          "PT_BENCH_FUSED": "1"}, 1200),
    "flash": (["flash"], _SKIP, 1800),
    "flash_train": (["flash_train"], _SKIP, 1800),
    # LLM serving decode path: paged-KV continuous batching vs dense
    # sequential generation (tokens/s + TTFT p50/p99); small model,
    # bounded token count — cheap enough for every campaign
    "llm_decode": (["llm_decode"], _SKIP, 600),
    # serving speed tier A/Bs: copy-on-write shared-prefix KV reuse
    # (admitted-streams x + kv_blocks_used vs unshared) and chunked
    # prefill (p99 inter-token with long-prompt arrivals, on vs off).
    # Both flags are [assumed off] until these land on-chip numbers.
    "llm_prefix_reuse": (["llm_prefix_reuse"], _SKIP, 600),
    "llm_mixed_prefill": (["llm_mixed_prefill"], _SKIP, 600),
    # multi-tenant isolation: premium TTFT p99 under a weight-1 bulk
    # flood with fair share on — the loaded/unloaded ratio the
    # llm_tenant_flood chaos drill gates at 1.25x
    "llm_tenant_flood": (["llm_tenant_flood"], _SKIP, 600),
    # speculative decoding (self-draft sanity config): accepted
    # tokens/s vs non-speculative, accept-rate + verify-latency
    # partials. FLAGS_speculative_k is [assumed off] until this lands
    # an on-chip number with a real (cheap) draft.
    "llm_spec_decode": (["llm_spec_decode"], _SKIP, 600),
    # tile-size sweep for the flash kernel (only worth chip time if the
    # default-tile flash_train stage loses to XLA)
    "flash_train_t128": (["flash_train"],
                         {**_SKIP, "FLAGS_flash_block_q": "128",
                          "FLAGS_flash_block_k": "128"}, 900),
    "flash_train_t512": (["flash_train"],
                         {**_SKIP, "FLAGS_flash_block_q": "512",
                          "FLAGS_flash_block_k": "512"}, 900),
    # round-3 regression hunt: fused_state measured -26% (b32), so the
    # remaining suspects for the 121.8k -> 97.1k/b32 gap are fused QKV
    # and per-chip batch. b8_perleaf_noqkv IS the round-2 config.
    "bert_b8_perleaf_noqkv": _bert(8, "0", "0"),
    "bert_b8_perleaf_qkv": _bert(8, "0", "1"),
    "bert_b16_perleaf_noqkv": _bert(16, "0", "0"),
    "bert_b32_perleaf_noqkv": _bert(32, "0", "0"),
    "resnet_nhwc_b128_perleaf": (
        ["resnet50"], {**_SKIP, **_SPL1, "FLAGS_batch_norm_single_pass": "0",
                       "PT_BENCH_RESNET_BATCH": "128",
                       "PT_BENCH_LAYOUT": "NHWC",
                       "PT_BENCH_FUSED": "0"}, 900),
    # clean fused-state A/B partner for _perleaf (same _SPL1 pinning —
    # the older resnet_nhwc_b128 stage autotunes steps-per-loop and is
    # not comparable like-for-like)
    "resnet_nhwc_b128_fused": (
        ["resnet50"], {**_SKIP, **_SPL1, "FLAGS_batch_norm_single_pass": "0",
                       "PT_BENCH_RESNET_BATCH": "128",
                       "PT_BENCH_LAYOUT": "NHWC",
                       "PT_BENCH_FUSED": "1"}, 900),
    "resnet_nhwc_b256_perleaf": (
        ["resnet50"], {**_SKIP, **_SPL1, "FLAGS_batch_norm_single_pass": "0",
                       "PT_BENCH_RESNET_BATCH": "256",
                       "PT_BENCH_LAYOUT": "NHWC",
                       "PT_BENCH_FUSED": "0"}, 900),
    # clean NCHW partner for resnet_nhwc_b128_perleaf (same _SPL1
    # pinning). The round-3 layout pin came from the unpinned pair, and
    # the dead NCHW stage's partial 8-step timing (75.76 ms vs NHWC
    # 77.42 in the same window) contradicts it — settle the layout with
    # a like-for-like pair (VERDICT r4 task 6).
    "resnet_nchw_b128_perleaf": (
        ["resnet50"], {**_SKIP, **_SPL1, "FLAGS_batch_norm_single_pass": "0",
                       "PT_BENCH_RESNET_BATCH": "128",
                       "PT_BENCH_LAYOUT": "NCHW",
                       "PT_BENCH_FUSED": "0"}, 900),
    "resnet_nhwc_b128_s2d": (
        ["resnet50"], {**_SKIP, **_SPL1, "FLAGS_batch_norm_single_pass": "0",
                       "PT_BENCH_RESNET_BATCH": "128",
                       "PT_BENCH_LAYOUT": "NHWC", "PT_BENCH_FUSED": "0",
                       "FLAGS_resnet_space_to_depth_stem": "1"}, 900),
    # BN-stat single-pass A/B partner for resnet_nhwc_b128_perleaf
    # (same pinning; only the flag differs)
    "resnet_bn1pass": (
        ["resnet50"], {**_SKIP, **_SPL1, "PT_BENCH_RESNET_BATCH": "128",
                       "PT_BENCH_LAYOUT": "NHWC", "PT_BENCH_FUSED": "0",
                       "FLAGS_batch_norm_single_pass": "1"}, 900),
    # dispatch-gap reclaim: the bn1pass profile shows 48.2 ms device
    # vs 52.1 ms wall — the SPL1 pinning of the lever ladder never
    # amortized the ~4 ms dispatch gap; a K=8 lax.scan dispatches once
    # per 8 optimizer steps and should reclaim most of it (measured:
    # 2582.6 vs 2455.9 img/s, +5.2%)
    "resnet_bn1pass_spl8": (
        ["resnet50"], {**_SKIP, "PT_BENCH_RESNET_BATCH": "128",
                       "PT_BENCH_LAYOUT": "NHWC", "PT_BENCH_FUSED": "0",
                       "FLAGS_batch_norm_single_pass": "1",
                       "PT_BENCH_STEPS_PER_LOOP": "8"}, 900),
    # flash batch ladder: under XLA attention the ladder peaked at b8
    # (the backward's [B,H,T,T] fp32 probs scale with batch); flash
    # removes that wall and the unpinned r5 sweep found b16 at 139.7k
    # (0.5856) — measure the ladder's new top. Default flags (flash
    # 512, BTHD, Pallas LN) + auto spl retiming.
    "bert_b16_flash": ([], {**_SKIP, "PT_BENCH_BERT_BATCH": "16",
                            "PT_BENCH_FUSED": "0"}, 900),
    "bert_b32_flash": ([], {**_SKIP, "PT_BENCH_BERT_BATCH": "32",
                            "PT_BENCH_FUSED": "0"}, 900),
    "bert_b64_flash": ([], {**_SKIP, "PT_BENCH_BERT_BATCH": "64",
                            "PT_BENCH_FUSED": "0"}, 900),
    "bert_b16_flash_maskedlm": ([], {**_SKIP,
                                     "PT_BENCH_BERT_BATCH": "16",
                                     "PT_BENCH_FUSED": "0",
                                     "PT_BENCH_MASKED_LM": "1"}, 900),
    # ISSUE 8 loss-region A/B at the b16 headline: fused MLM-head+xent
    # kernel (never materializes the [B,T,V] logits) vs bert_b16_flash,
    # then the fused-Adam default candidate stacked on top of it
    "bert_b16_fusedloss": ([], {**_SKIP, "PT_BENCH_BERT_BATCH": "16",
                                "PT_BENCH_FUSED": "0",
                                "FLAGS_fused_softmax_xent": "1"}, 900),
    "bert_b16_fusedloss_fusedadam": ([], {**_SKIP,
                                          "PT_BENCH_BERT_BATCH": "16",
                                          "PT_BENCH_FUSED": "0",
                                          "FLAGS_fused_softmax_xent":
                                          "1",
                                          "FLAGS_fused_adam": "1"},
                                     900),
    # ladder midpoint: b16 139.3k > b32 136.1k — the peak may sit
    # between
    "bert_b24_flash": ([], {**_SKIP, "PT_BENCH_BERT_BATCH": "24",
                            "PT_BENCH_FUSED": "0"}, 900),
    # where do the remaining ~53% of peak go at the new headline config
    "profile_bert_b16_flash": (["bert", "16"], {}, 900,
                               "tools/profile_step.py"),
    # steps-per-loop ladder top: does K=32 add anything over K=8's
    # +1.4% at the BERT headline config
    "bert_b8_flash512_spl32": ([], {**_SKIP,
                                    "PT_BENCH_BERT_BATCH": "8",
                                    "PT_BENCH_FUSED": "0",
                                    "FLAGS_fused_qkv_projection": "0",
                                    "FLAGS_flash_attention_min_seq_train":
                                    "512",
                                    "FLAGS_attention_bthd_layout": "0",
                                    "PT_BENCH_STEPS_PER_LOOP": "32"},
                               900),
    # block remat on the HBM-bound step: recompute FLOPs ride idle MXU
    # while intermediate activations skip the HBM round-trip — A/B
    # partner is resnet_bn1pass_spl8 (identical env, only the flag)
    "resnet_remat": (
        ["resnet50"], {**_SKIP, "PT_BENCH_RESNET_BATCH": "128",
                       "PT_BENCH_LAYOUT": "NHWC", "PT_BENCH_FUSED": "0",
                       "FLAGS_batch_norm_single_pass": "1",
                       "FLAGS_resnet_block_remat": "1",
                       "PT_BENCH_STEPS_PER_LOOP": "8"}, 900),
    # stack the two stem/stat levers on top of the bn1pass win (+8.5%
    # measured): s2d alone was +0.8% (noise) — see if it adds anything
    # once BN stats no longer dominate the loop fusions
    "resnet_bn1pass_s2d": (
        ["resnet50"], {**_SKIP, **_SPL1, "PT_BENCH_RESNET_BATCH": "128",
                       "PT_BENCH_LAYOUT": "NHWC", "PT_BENCH_FUSED": "0",
                       "FLAGS_batch_norm_single_pass": "1",
                       "FLAGS_resnet_space_to_depth_stem": "1"}, 900),
    # post-bn1pass profile: where do the reclaimed ms go / what is the
    # new category budget (conv share should rise toward the HBM bound)
    "profile_resnet_bn1pass": (["resnet", "128"],
                               {"PT_PROF_LAYOUT": "NHWC",
                                "FLAGS_batch_norm_single_pass": "1"},
                               900, "tools/profile_step.py"),
    # low end of the BERT batch ladder (r5 measured b8 121.1k > b16
    # 106.4k > b32 100.6k — monotonic toward small batch, so probe b4)
    "bert_b4_perleaf_noqkv": _bert(4, "0", "0"),
    # in-model flash routing at BERT's own seq 512: the standalone r5
    # sweep says flash wins at every seq incl. 512 (8.68x), but both
    # standalone numbers at T512 are dispatch-overhead-dominated — only
    # an in-model step A/B against bert_b8_perleaf_noqkv settles the
    # train gate
    "bert_b8_flash512": ([], {**_bert(8, "0", "0")[1],
                              "FLAGS_flash_attention_min_seq_train":
                              "512",
                              "FLAGS_attention_bthd_layout": "0"}, 900),
    # BTHD-native flash layout (zero physical head transposes; the
    # kernel gathers heads in its block DMA): the layout flag is the
    # ONLY difference vs bert_b8_flash512, so the A/B stays pinnable
    # on any code version
    "bert_b8_flash_bthd": ([], {**_bert(8, "0", "0")[1],
                                "FLAGS_flash_attention_min_seq_train":
                                "512",
                                "FLAGS_attention_bthd_layout": "1"},
                           900),
    # dispatch-copy amortization at the NEW best config (flash512):
    # the only prior steps-per-loop A/B (0.95x) was at fused_b32 —
    # per-leaf b8 has far more dispatch buffers, so re-measure there
    "bert_b8_flash512_spl8": ([], {**_SKIP,
                                   "PT_BENCH_BERT_BATCH": "8",
                                   "PT_BENCH_FUSED": "0",
                                   "FLAGS_fused_qkv_projection": "0",
                                   "FLAGS_flash_attention_min_seq_train":
                                   "512",
                                   "FLAGS_attention_bthd_layout": "0",
                                   "PT_BENCH_STEPS_PER_LOOP": "8"}, 900),
    # flash512 at the b4 ladder point (only worth running if plain b4
    # lands within noise of b8)
    "bert_b4_flash512": ([], {**_bert(4, "0", "0")[1],
                              "FLAGS_flash_attention_min_seq_train":
                              "512"}, 900),
    # Pallas-vs-XLA LayerNorm at the best config (use_pallas_layer_norm
    # has been default-on [assumed] since round 2 with zero chip
    # evidence; the r5 HLO metadata probe shows the per-layer backward
    # pallas_call fusions at ~0.2 ms each). A/B partner:
    # bert_b8_flash512_spl8 — identical env, only the LN route differs.
    "bert_b8_spl8_xlaln": ([], {**_SKIP,
                                "PT_BENCH_BERT_BATCH": "8",
                                "PT_BENCH_FUSED": "0",
                                "FLAGS_fused_qkv_projection": "0",
                                "FLAGS_flash_attention_min_seq_train":
                                "512",
                                "FLAGS_attention_bthd_layout": "0",
                                "FLAGS_use_pallas_layer_norm": "0",
                                "PT_BENCH_STEPS_PER_LOOP": "8"}, 900),
    "bert_b32_remat": ([], {**_SKIP, **_SPL1,
                            "FLAGS_flash_attention_min_seq_train": "1024",
                            "PT_BENCH_BERT_BATCH": "32",
                            "PT_BENCH_FUSED": "0",
                            "FLAGS_fused_qkv_projection": "0",
                            "FLAGS_transformer_remat": "1"}, 900),
    "bert_b64_remat": ([], {**_SKIP, **_SPL1,
                            "FLAGS_flash_attention_min_seq_train": "1024",
                            "PT_BENCH_BERT_BATCH": "64",
                            "PT_BENCH_FUSED": "0",
                            "FLAGS_fused_qkv_projection": "0",
                            "FLAGS_transformer_remat": "1"}, 900),
    "bert_b8_bf16mv": ([], {**_SKIP, **_SPL1,
                            "FLAGS_flash_attention_min_seq_train": "1024",
                            "PT_BENCH_BERT_BATCH": "8",
                            "PT_BENCH_FUSED": "0",
                            "FLAGS_fused_qkv_projection": "0",
                            "FLAGS_optimizer_moment_dtype": "bfloat16"},
                       900),
    # masked-LM head restriction (reference-parity mask_pos gather):
    # A/B against bert_b{32,8}_perleaf_noqkv — SAME baseline env via
    # _bert so the comparison stays single-variable
    "bert_b32_maskedlm": ([], {**_bert(32, "0", "0")[1],
                               "PT_BENCH_MASKED_LM": "1"}, 900),
    "bert_b8_maskedlm": ([], {**_bert(8, "0", "0")[1],
                              "PT_BENCH_MASKED_LM": "1"}, 900),
    # framework-free raw-JAX RN50 comparator: same chip, no paddle_tpu
    # on the model path — separates "our overhead" from "XLA's conv
    # ceiling" for the stuck ~2260 img/s
    "rn50_floor": (["128"], {}, 900, "tools/rn50_floor.py"),
    # Profile stages pin the config they historically profiled (same
    # no-silent-config-change rule as the bench stages): profile_bert
    # is the XLA-attention transpose-layout baseline whose rollup
    # steered rounds 2-5; profile_bert_flash is the current default
    # config (flash512 + BTHD). profile_resnet is the two-pass-BN
    # baseline; profile_resnet_bn1pass the measured winner.
    "profile_bert": (["bert", "8"],
                     {"FLAGS_flash_attention_min_seq_train": "1024",
                      "FLAGS_attention_bthd_layout": "0"},
                     900, "tools/profile_step.py"),
    "profile_bert_flash": (["bert", "8"], {}, 900,
                           "tools/profile_step.py"),
    "profile_bert_b32": (["bert", "32"],
                         {"FLAGS_flash_attention_min_seq_train": "1024",
                          "FLAGS_attention_bthd_layout": "0"}, 900,
                         "tools/profile_step.py"),
    "profile_resnet": (["resnet", "128"],
                       {"PT_PROF_LAYOUT": "NHWC",
                        "FLAGS_batch_norm_single_pass": "0"}, 900,
                       "tools/profile_step.py"),
    # unpinned autotunes (the driver's default bench path)
    "bert": ([], {}, 3000),
    "resnet": (["resnet50"], {}, 3000),
}
DEFAULT_PLAN = ["verify", "bert_fused_b32", "resnet_nhwc_b128",
                "bert_perleaf_b32", "resnet_nchw_b128", "flash"]
DIAG_PLAN = ["bert_b8_perleaf_noqkv", "bert_b8_perleaf_qkv",
             "bert_b16_perleaf_noqkv", "bert_b32_perleaf_noqkv",
             "resnet_nhwc_b128_perleaf", "flash", "flash_train",
             "profile_bert", "profile_bert_b32", "profile_resnet",
             "resnet_nhwc_b256_perleaf", "resnet_nhwc_b128_s2d",
             "bert_b32_remat", "bert_b64_remat", "bert_b8_bf16mv"]
# Round-4 triage (VERDICT r3 task 5): ordered by information value per
# chip-minute so the first ~15 min of any tunnel window settles the big
# questions — b8-vs-b32 (the 121.8k discrepancy), the ResNet levers
# (largest perf hole), and the flash train crossover — before the tail.
R4_PLAN = ["verify",                      # refresh stamped artifact
           "bert_b8_perleaf_noqkv",       # the round-2 121.8k config
           "bert_b8_perleaf_qkv",
           "resnet_nhwc_b128_perleaf",
           "resnet_nhwc_b128_s2d",
           "bert_b32_perleaf_noqkv",
           "bert_b32_maskedlm",           # ~20% FLOP cut if it holds
           "flash_train",
           "bert_b8_bf16mv",
           "bert_b8_maskedlm",
           "bert_b16_perleaf_noqkv",
           "resnet_nhwc_b128_fused",
           "resnet_nhwc_b256_perleaf",
           "bert_b32_remat",
           "bert_b64_remat",
           "flash",
           "flash_train_t128", "flash_train_t512",
           "profile_bert", "profile_bert_b32", "profile_resnet"]
# Round-5 triage (VERDICT r4 "Next round"): ResNet is the project's
# largest hole (0.14 vs ≥0.5 bar, zero profile evidence) — so the
# FIRST chip-minutes go to the ResNet rollup, then the lever ladder
# with the clean NCHW pair (task 6), then a stamped verify refresh
# (the r3 VERIFY_TPU.json predates device/kernel-hash stamping, so the
# driver's bench would otherwise revalidate), then the BERT b8-vs-b32 +
# masked-LM matrix (task 3), flash prove-or-retire (task 4), and the
# tail. The final unpinned bert/resnet stages pre-warm the driver's
# exact flows.
R5_PLAN = ["profile_resnet",
           "resnet_nhwc_b128_perleaf",
           "resnet_nchw_b128_perleaf",
           "resnet_nhwc_b128_s2d",
           "resnet_nhwc_b256_perleaf",
           "verify",
           "bert_b8_perleaf_noqkv",
           "bert_b32_perleaf_noqkv",
           "bert_b32_maskedlm",
           "bert_b8_maskedlm",
           "bert_b8_bf16mv",
           "flash_train",
           "bert_b8_perleaf_qkv",
           "bert_b16_perleaf_noqkv",
           "resnet_nhwc_b128_fused",
           "bert_b32_remat",
           "bert_b64_remat",
           "flash",
           "flash_train_t128", "flash_train_t512",
           "profile_bert_b32", "profile_bert",
           "bert", "resnet"]


def log(msg: str) -> None:
    print(f"[capture] {msg}", file=sys.stderr, flush=True)


def _text(v) -> str:
    if v is None:
        return ""
    if isinstance(v, bytes):
        return v.decode("utf-8", "replace")
    return v


def run_stage(name: str) -> dict:
    spec = STAGES[name]
    args, env, budget = spec[:3]
    script = spec[3] if len(spec) > 3 else "bench.py"
    t0 = time.time()
    log(f"stage {name}: starting (budget {budget}s)")
    stdout, stderr, rc, timed_out = "", "", None, False
    try:
        # tell bench.py its real deadline (minus a margin for probe +
        # import) so its soft-budget bails fire BEFORE the hard kill —
        # a stage that overruns still emits its best-so-far JSON line
        stage_env = {"PT_BENCH_BUDGET_S": str(max(60, budget - 120)),
                     **env}
        r = subprocess.run(
            [sys.executable, os.path.join(ROOT, script), *args],
            capture_output=True, text=True, timeout=budget, cwd=ROOT,
            env={**os.environ, **stage_env})
        stdout, stderr, rc = r.stdout, r.stderr, r.returncode
    except subprocess.TimeoutExpired as e:
        # partial output is the whole point: bench.py logs each
        # config's ms/step to stderr as it measures
        stdout, stderr = _text(e.stdout), _text(e.stderr)
        timed_out = True
        log(f"stage {name}: TIMED OUT after {budget}s "
            f"(keeping partial output)")
    parsed = None
    for line in (stdout or "").splitlines():
        line = line.strip()
        if line.startswith("{"):
            try:
                parsed = json.loads(line)
            except json.JSONDecodeError:
                continue
    # a stage that printed its result JSON and then wedged (timeout) or
    # crashed (negative rc = signal) in PJRT teardown still produced a
    # usable measurement — but a DELIBERATE failure exit (verify prints
    # value 0.0 then sys.exit(1)) must stay not-ok so the watcher
    # retries it. Profiler stages emit a text rollup: rc==0 is their ok.
    stage_ok = (parsed is not None
                and (rc == 0 or timed_out
                     or (rc is not None and rc < 0))) or \
        (script != "bench.py" and rc == 0)
    out = {"stage": name,
           "ok": stage_ok,
           "rc": rc, "timed_out": timed_out, "parsed": parsed,
           "elapsed_s": round(time.time() - t0, 1),
           "env": env,
           # 90 lines keeps a full profiler rollup (categories + top-30
           # table) — 45 cut the category header off every profile
           # artifact this round
           "stdout_tail": (stdout or "").splitlines()[-90:],
           "stderr_tail": (stderr or "").splitlines()[-40:]}
    result_path = os.path.join(ROOT, f"CAPTURE_{name}.json")
    with open(result_path, "w") as f:
        json.dump(out, f, indent=1)
    log(f"stage {name}: rc={rc} parsed={parsed} "
        f"({out['elapsed_s']}s) -> {result_path}")
    return out


def resolve_plan(names: list) -> list:
    """Expand plan aliases ('default', 'diag') into stage lists; shared
    with tunnel_watch so both entry points accept the same argv."""
    out: list = []
    for n in names:
        if n == "default":
            out.extend(DEFAULT_PLAN)
        elif n == "diag":
            out.extend(DIAG_PLAN)
        elif n == "r4":
            out.extend(R4_PLAN)
        elif n == "r5":
            out.extend(R5_PLAN)
        else:
            out.append(n)
    return out


def main() -> None:
    wanted = resolve_plan(sys.argv[1:] or list(DEFAULT_PLAN))
    unknown = [w for w in wanted if w not in STAGES]
    if unknown:
        raise SystemExit(f"unknown stages {unknown}; pick from "
                         f"{sorted(STAGES)}")
    results = [run_stage(name) for name in wanted]
    # merge into any existing summary so a retry campaign over the
    # remaining stages doesn't erase earlier stages' records
    summary_path = os.path.join(ROOT, "CAPTURE_SUMMARY.json")
    by_stage: dict = {}
    try:
        with open(summary_path) as f:
            for r in json.load(f).get("results", []):
                by_stage[r.get("stage")] = r
    except (OSError, json.JSONDecodeError):
        pass
    for r in results:
        by_stage[r["stage"]] = r
    summary = {"when": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
               "results": list(by_stage.values())}
    with open(summary_path, "w") as f:
        json.dump(summary, f, indent=1)
    log(f"campaign done: {[(r['stage'], r['ok']) for r in results]}")
    sys.exit(0 if all(r["ok"] for r in results) else 1)


if __name__ == "__main__":
    main()
