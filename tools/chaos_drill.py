#!/usr/bin/env python
"""Chaos drills: prove the fault-tolerance layer end to end.

Each drill runs real training subprocesses with deterministic fault
injection (FLAGS_fault_spec, paddle_tpu.testing.faults) and asserts
the recovery contract from docs/fault_tolerance.md:

  kill_mid_save    — SIGKILL lands mid checkpoint write; the strand is
                     never visible as a checkpoint and a restart
                     resumes from the newest INTACT one.
  corrupt_leaf     — a leaf's bytes are flipped on disk; restore
                     detects the CRC mismatch, falls back one step,
                     and records checkpoint_corrupt_total + a flight
                     event. A stripped COMMIT marker falls back again.
  sigterm_mid_fit  — graceful preemption: SIGTERM during Model.fit
                     finishes the step, forces a final checkpoint,
                     dies with the SIGTERM wait status, and the
                     restart resumes at the preempted step.
  crash_loop       — a deterministic per-step crash under
                     launch_elastic terminates via the sliding-window
                     restart budget instead of exhausting max_restarts.
  nonfinite_skip   — injected non-finite gradients (value fault
                     nonfinite_grad) are skipped in-graph by the
                     skip-step guard: fit completes, weights stay
                     finite, nonfinite_steps_total counts the skips.
  exact_resume     — SIGKILL mid-epoch, resume from the newest intact
                     v3 checkpoint (RNG stream + data offset +
                     GradScaler state restored): final weights are
                     BITWISE-identical to an uninterrupted control run
                     (delegates to tools/replay_check.py).

Usage:
  python tools/chaos_drill.py --self-test        # all drills (CPU)
  python tools/chaos_drill.py --drill kill_mid_save
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import shutil
import signal
import subprocess
import sys
import tempfile
import textwrap
import time

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if ROOT not in sys.path:  # runnable from any cwd
    sys.path.insert(0, ROOT)

# Per-step auto-checkpointing trainer driven entirely by env flags;
# writes {"resumed": <step>, "attempt": N} to its output path before
# training so the driver can assert the resume point.
_TRAINER = textwrap.dedent("""
    import json, os, sys
    import numpy as np
    import jax
    jax.config.update("jax_platforms", "cpu")
    import paddle_tpu as pt
    from paddle_tpu import io
    from paddle_tpu.sysconfig import enable_compile_cache

    enable_compile_cache()
    ckdir, outpath = sys.argv[1], sys.argv[2]
    n_steps = int(sys.argv[3]) if len(sys.argv) > 3 else 12

    rng = np.random.default_rng(0)
    batches = [(rng.normal(size=(8, 4)).astype(np.float32),
                rng.integers(0, 2, (8,)).astype(np.int64))
               for _ in range(n_steps)]
    pt.seed(0)
    net = pt.nn.Linear(4, 2)
    model = pt.hapi.Model(
        net, loss=lambda o, y: pt.nn.functional.cross_entropy(o, y),
        optimizer=pt.optimizer.SGD(learning_rate=0.1))
    resumed = io.AsyncCheckpointer(ckdir).latest_step() or 0
    with open(outpath, "w") as f:
        json.dump({"resumed": resumed,
                   "attempt": int(os.environ.get("PT_ELASTIC_ATTEMPT",
                                                 "0"))}, f)
    model.fit(batches, epochs=1, verbose=0, ckpt_dir=ckdir,
              save_steps=2)
    with open(outpath, "w") as f:
        json.dump({"resumed": resumed, "done": True,
                   "attempt": int(os.environ.get("PT_ELASTIC_ATTEMPT",
                                                 "0"))}, f)
""")


class DrillFailure(AssertionError):
    pass


def _check(cond, msg):
    if not cond:
        raise DrillFailure(msg)


def _env(tmp, fault_spec=None):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = ROOT + os.pathsep + env.get("PYTHONPATH", "")
    env["FLAGS_enable_metrics"] = "1"
    env["FLAGS_metrics_port"] = "-1"        # no HTTP exporter in drills
    env["FLAGS_trace_dir"] = os.path.join(tmp, "trace")
    if fault_spec:
        env["FLAGS_fault_spec"] = fault_spec
    else:
        env.pop("FLAGS_fault_spec", None)
    return env


def _run_trainer(tmp, ckdir, fault_spec=None, steps=12, timeout=240):
    script = os.path.join(tmp, "trainer.py")
    if not os.path.exists(script):
        with open(script, "w") as f:
            f.write(_TRAINER)
    out = os.path.join(tmp, "result.json")
    if os.path.exists(out):
        os.remove(out)
    proc = subprocess.run(
        [sys.executable, script, ckdir, out, str(steps)],
        env=_env(tmp, fault_spec), capture_output=True, text=True,
        timeout=timeout)
    result = json.load(open(out)) if os.path.exists(out) else {}
    return proc, result


def _intact_checkpoints(ckdir):
    from paddle_tpu import io
    ck = io.AsyncCheckpointer(ckdir)
    return {s: io.verify(os.path.join(ckdir, f"ckpt-{s}"))
            for s in ck.intact_steps()}


# --------------------------------------------------------------- drills

def drill_kill_mid_save(tmp):
    """SIGKILL fired by the checkpoint writer mid-save of step 8."""
    ck = os.path.join(tmp, "ck_kill")
    p1, _ = _run_trainer(tmp, ck, fault_spec="ckpt_write:step=8:kill=9")
    _check(p1.returncode == -signal.SIGKILL,
           f"expected SIGKILL death, rc={p1.returncode}\n{p1.stderr}")
    from paddle_tpu import io
    latest = io.AsyncCheckpointer(ck).latest_step()
    _check(latest == 6, f"newest intact checkpoint should be 6, "
           f"got {latest} ({sorted(os.listdir(ck))})")
    p2, res = _run_trainer(tmp, ck)
    _check(p2.returncode == 0, f"restart failed rc={p2.returncode}\n"
           f"{p2.stderr}")
    _check(res.get("resumed") == 6 and res.get("done"),
           f"restart should resume from 6 and finish, got {res}")
    reports = _intact_checkpoints(ck)
    _check(reports and all(not v for v in reports.values()),
           f"post-restart checkpoints not intact: {reports}")
    _check(not glob.glob(os.path.join(ck, "*.tmp")),
           "stale .tmp staging dir survived the restart")
    return f"killed mid ckpt-8 write, resumed from 6, finished clean"


def drill_corrupt_leaf(tmp):
    """Bit-flip the newest checkpoint; restore falls back one step."""
    ck = os.path.join(tmp, "ck_corrupt")
    p1, _ = _run_trainer(tmp, ck)
    _check(p1.returncode == 0, f"clean run failed\n{p1.stderr}")
    from paddle_tpu import io
    from paddle_tpu.observability import flight, metrics
    ckptr = io.AsyncCheckpointer(ck)
    steps = ckptr.intact_steps()
    _check(len(steps) >= 2, f"need >=2 checkpoints, got {steps}")
    newest, fallback = steps[-1], steps[-2]
    leaf = sorted(glob.glob(os.path.join(ck, f"ckpt-{newest}",
                                         "data", "*.npy")))[0]
    raw = open(leaf, "rb").read()
    with open(leaf, "wb") as f:       # same size, different bytes
        f.write(raw[:-1] + bytes([raw[-1] ^ 0xFF]))
    _check(io.verify(os.path.join(ck, f"ckpt-{newest}")),
           "verify() missed the corrupted leaf")
    before = metrics.counter("checkpoint_corrupt_total",
                              always=True).value()
    state, got = ckptr.restore_latest()
    _check(got == fallback and state is not None,
           f"restore should fall back to {fallback}, got {got}")
    _check(metrics.counter("checkpoint_corrupt_total",
                           always=True).value()
           == before + 1, "checkpoint_corrupt_total did not increment")
    events = [e for e in flight.recorder().events()
              if e.get("kind") == "checkpoint_corrupt"]
    _check(events, "no checkpoint_corrupt flight event recorded")
    # a stripped COMMIT marker must also be skipped
    os.remove(os.path.join(ck, f"ckpt-{fallback}", "COMMIT"))
    _, got2 = ckptr.restore_latest()
    _check(got2 is not None and got2 < fallback,
           f"uncommitted fallback not skipped, got {got2}")
    return (f"corrupt ckpt-{newest} fell back to {fallback}; "
            f"stripped COMMIT fell back to {got2}; counter+event ok")


def drill_sigterm_mid_fit(tmp):
    """Scheduler preemption at train step 7, resume where it died."""
    ck = os.path.join(tmp, "ck_term")
    p1, _ = _run_trainer(tmp, ck, fault_spec="sigterm:step=7")
    _check(p1.returncode in (-signal.SIGTERM, 128 + signal.SIGTERM),
           f"expected SIGTERM wait status, rc={p1.returncode}\n"
           f"{p1.stderr}")
    from paddle_tpu import io
    latest = io.AsyncCheckpointer(ck).latest_step()
    _check(latest == 8, f"preemption checkpoint should land at 8 "
           f"(step 7 finished), got {latest}")
    dumps = glob.glob(os.path.join(tmp, "trace", "flight_*.jsonl"))
    _check(dumps, "no flight dump written on preemption")
    dump_text = "".join(open(d).read() for d in dumps)
    _check("preemption_notice" in dump_text,
           "flight dump lacks the preemption_notice event")
    _check("preempt_checkpoint" in dump_text,
           "flight dump lacks the preempt_checkpoint event")
    p2, res = _run_trainer(tmp, ck)
    _check(p2.returncode == 0 and res.get("resumed") == 8
           and res.get("done"),
           f"restart should resume from 8 and finish, got "
           f"rc={p2.returncode} {res}")
    return "preempted after step 7, checkpointed at 8, resumed at 8"


def drill_crash_loop(tmp):
    """Deterministic crash at step 3; the restart budget fails fast."""
    from paddle_tpu.distributed.launch import launch_elastic
    ck = os.path.join(tmp, "ck_loop")
    script = os.path.join(tmp, "trainer.py")
    if not os.path.exists(script):
        with open(script, "w") as f:
            f.write(_TRAINER)
    out = os.path.join(tmp, "loop_result.json")
    log = os.path.join(tmp, "loop_attempts.log")
    env = _env(tmp, fault_spec="train_step:step=3:exc=RuntimeError")
    t0 = time.time()
    rc = launch_elastic(
        [sys.executable, script, ck, out, "12"], nproc=1,
        max_restarts=8, env_extra=env, backoff_s=0.05,
        backoff_max_s=0.2, restart_budget=2, restart_window_s=60.0)
    elapsed = time.time() - t0
    _check(rc != 0, "crash loop unexpectedly converged")
    attempts = json.load(open(out)).get("attempt")
    _check(attempts == 2,
           f"budget of 2 should stop after attempts 0,1,2 — last "
           f"attempt was {attempts}")
    from paddle_tpu.observability import metrics
    _check(metrics.counter("elastic_budget_exhausted_total",
                           always=True).value()
           >= 1, "budget-exhausted counter not incremented")
    return (f"crash-loop stopped by budget after 3 attempts "
            f"({elapsed:.1f}s), not max_restarts=8")


# Skip-guard trainer: reports the nonfinite counter + weight health
# so the driver can assert the skips actually happened in-graph.
_NONFINITE_TRAINER = textwrap.dedent("""
    import json, sys
    import numpy as np
    import jax
    jax.config.update("jax_platforms", "cpu")
    import paddle_tpu as pt
    from paddle_tpu.observability import metrics
    from paddle_tpu.sysconfig import enable_compile_cache

    enable_compile_cache()
    outpath = sys.argv[1]
    rng = np.random.default_rng(0)
    batches = [(rng.normal(size=(8, 4)).astype(np.float32),
                rng.integers(0, 2, (8,)).astype(np.int64))
               for _ in range(10)]
    pt.seed(0)
    net = pt.nn.Linear(4, 2)
    model = pt.hapi.Model(
        net, loss=lambda o, y: pt.nn.functional.cross_entropy(o, y),
        optimizer=pt.optimizer.SGD(learning_rate=0.1))
    hist = model.fit(batches, epochs=1, verbose=0)
    jax.effects_barrier()   # drain the async nonfinite-step callbacks
    w = {k: np.asarray(v) for k, v in net.state_dict().items()}
    with open(outpath, "w") as f:
        json.dump({
            "done": True,
            "nonfinite_steps": metrics.counter(
                "nonfinite_steps_total", always=True).value(),
            "weights_finite": bool(all(np.isfinite(a).all()
                                       for a in w.values())),
            "loss_finite": bool(np.isfinite(hist["loss"][-1])),
        }, f)
""")


def drill_nonfinite_skip(tmp):
    """Two injected NaN-gradient steps must be skipped in-graph."""
    script = os.path.join(tmp, "nonfinite_trainer.py")
    with open(script, "w") as f:
        f.write(_NONFINITE_TRAINER)
    out = os.path.join(tmp, "nonfinite_result.json")
    proc = subprocess.run(
        [sys.executable, script, out],
        env=_env(tmp, fault_spec="nonfinite_grad:step=3,"
                                 "nonfinite_grad:step=6"),
        capture_output=True, text=True, timeout=240)
    _check(proc.returncode == 0,
           f"skip-guard trainer died rc={proc.returncode}\n"
           f"{proc.stderr}")
    res = json.load(open(out))
    _check(res.get("done"), f"trainer did not finish: {res}")
    _check(res.get("nonfinite_steps", 0) >= 2,
           f"nonfinite_steps_total should be >= 2, got "
           f"{res.get('nonfinite_steps')}")
    _check(res.get("weights_finite"),
           "weights went non-finite despite the skip guard")
    _check(res.get("loss_finite"), "epoch loss went non-finite")
    return (f"{res['nonfinite_steps']} nonfinite-grad steps skipped "
            "in-graph, weights finite, fit completed")


_STREAM_DISCONNECT = r"""
import json, socket, sys, time
import paddle_tpu as pt
from paddle_tpu import observability as obs
from paddle_tpu.inference import Client, Server
from paddle_tpu.models import GPTLanguageModel
from paddle_tpu.serving_llm import LLMEngine

out = sys.argv[1]
model = GPTLanguageModel()
engine = LLMEngine(model, block_size=4, pool_blocks=32)
srv = Server(None, llm_engine=engine)
cli = Client(port=srv.port, timeout_s=60.0)
# ask for far more tokens than we will read, then vanish mid-stream
gen = cli.generate_stream([7] * 9, max_new_tokens=200)
got = [int(next(gen)[0]) for _ in range(2)]
used_mid = engine.allocator.num_used
cli._sock.close()                     # abrupt close, no goodbye frame
# server notices on its next chunk write (rc=-3) and cancels the
# sequence; give it a bounded window to quiesce
deadline = time.time() + 60
while engine.active() and time.time() < deadline:
    time.sleep(0.1)
leak_check = True
try:
    engine.allocator.check()
except AssertionError:
    leak_check = False
res = {
    "tokens_read": len(got),
    "used_mid_stream": used_mid,
    "active_after": engine.active(),
    "kv_used_after": engine.allocator.num_used,
    "kv_used_gauge": obs.gauge("kv_blocks_used").value(),
    "kv_freed_total": engine.allocator.freed_total,
    "allocator_check_ok": leak_check,
    "cancelled_total": obs.counter(
        "serving_stream_cancelled_total").value(),
    "shed_total": obs.counter("requests_shed_total").value(),
    "flight_cancel_events": sum(
        1 for e in obs.flight.recorder().events()
        if e.get("kind") == "serving_stream_cancelled"),
}
srv.stop()
json.dump(res, open(out, "w"))
"""


def drill_stream_disconnect(tmp):
    """Streaming client vanishes mid-generation: the serving loop must
    cancel the sequence and return every KV block to the pool — no
    leak, and the disconnect is a *cancel*, never a *shed*."""
    script = os.path.join(tmp, "stream_disconnect.py")
    with open(script, "w") as f:
        f.write(_STREAM_DISCONNECT)
    out = os.path.join(tmp, "stream_disconnect.json")
    proc = subprocess.run(
        [sys.executable, script, out], env=_env(tmp),
        capture_output=True, text=True, timeout=240)
    _check(proc.returncode == 0,
           f"stream-disconnect run died rc={proc.returncode}\n"
           f"{proc.stderr}")
    res = json.load(open(out))
    _check(res["tokens_read"] == 2 and res["used_mid_stream"] > 0,
           f"stream never got going: {res}")
    _check(not res["active_after"],
           f"engine still active after disconnect: {res}")
    _check(res["kv_used_after"] == 0 and res["kv_used_gauge"] == 0.0,
           f"KV blocks leaked after disconnect: {res}")
    _check(res["allocator_check_ok"],
           f"allocator invariant audit failed: {res}")
    _check(res["cancelled_total"] >= 1,
           f"serving_stream_cancelled_total not counted: {res}")
    _check(res["flight_cancel_events"] >= 1,
           f"no serving_stream_cancelled flight event: {res}")
    _check(res["shed_total"] == 0,
           f"disconnect was miscounted as a shed: {res}")
    return (f"client vanished after {res['tokens_read']} tokens; "
            f"{res['kv_freed_total']} KV blocks freed, pool clean, "
            f"cancel counted (sheds untouched)")


def drill_exact_resume(tmp):
    """SIGKILL mid-epoch + v3 resume == uninterrupted run, bitwise."""
    try:
        from tools import replay_check
    except ImportError:  # run from inside tools/
        import replay_check
    try:
        return replay_check.run_check(tmp)
    except replay_check.CheckFailure as e:
        raise DrillFailure(str(e)) from e


DRILLS = {
    "kill_mid_save": drill_kill_mid_save,
    "corrupt_leaf": drill_corrupt_leaf,
    "sigterm_mid_fit": drill_sigterm_mid_fit,
    "crash_loop": drill_crash_loop,
    "nonfinite_skip": drill_nonfinite_skip,
    "exact_resume": drill_exact_resume,
    "stream_disconnect": drill_stream_disconnect,
}


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--self-test", action="store_true",
                        help="run every drill on CPU and report")
    parser.add_argument("--drill", choices=sorted(DRILLS),
                        help="run one drill")
    parser.add_argument("--keep", action="store_true",
                        help="keep the scratch directory")
    args = parser.parse_args(argv)
    if not args.self_test and not args.drill:
        parser.error("pass --self-test or --drill NAME")

    # the driver half imports paddle_tpu itself — force CPU first
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax
    jax.config.update("jax_platforms", "cpu")

    names = [args.drill] if args.drill else sorted(DRILLS)
    tmp = tempfile.mkdtemp(prefix="chaos_drill_")
    failures = 0
    try:
        for name in names:
            t0 = time.time()
            try:
                summary = DRILLS[name](tmp)
                print(f"[chaos] {name}: OK ({time.time() - t0:.1f}s) — "
                      f"{summary}")
            except DrillFailure as e:
                failures += 1
                print(f"[chaos] {name}: FAIL — {e}", file=sys.stderr)
    finally:
        if args.keep:
            print(f"[chaos] scratch kept at {tmp}")
        else:
            shutil.rmtree(tmp, ignore_errors=True)
    if failures:
        print(f"chaos drill: {failures} of {len(names)} drills FAILED",
              file=sys.stderr)
        return 1
    print(f"chaos drill self-test OK ({len(names)} drills)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
