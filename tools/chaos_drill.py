#!/usr/bin/env python
"""Chaos drills: prove the fault-tolerance layer end to end.

Each drill runs real training subprocesses with deterministic fault
injection (FLAGS_fault_spec, paddle_tpu.testing.faults) and asserts
the recovery contract from docs/fault_tolerance.md:

  kill_mid_save    — SIGKILL lands mid checkpoint write; the strand is
                     never visible as a checkpoint and a restart
                     resumes from the newest INTACT one.
  corrupt_leaf     — a leaf's bytes are flipped on disk; restore
                     detects the CRC mismatch, falls back one step,
                     and records checkpoint_corrupt_total + a flight
                     event. A stripped COMMIT marker falls back again.
  sigterm_mid_fit  — graceful preemption: SIGTERM during Model.fit
                     finishes the step, forces a final checkpoint,
                     dies with the SIGTERM wait status, and the
                     restart resumes at the preempted step.
  crash_loop       — a deterministic per-step crash under
                     launch_elastic terminates via the sliding-window
                     restart budget instead of exhausting max_restarts.
  nonfinite_skip   — injected non-finite gradients (value fault
                     nonfinite_grad) are skipped in-graph by the
                     skip-step guard: fit completes, weights stay
                     finite, nonfinite_steps_total counts the skips.
  exact_resume     — SIGKILL mid-epoch, resume from the newest intact
                     v3 checkpoint (RNG stream + data offset +
                     GradScaler state restored): final weights are
                     BITWISE-identical to an uninterrupted control run
                     (delegates to tools/replay_check.py).
  llm_overload_shed — a stream flood beyond the KV admission watermark
                     is refused AT ADMISSION (retry_after_ms hint in
                     the error payload, llm_admission_rejected_total
                     counted, zero preemptions) while admitted streams
                     decode to exact dense parity and the pool drains
                     to zero.
  llm_tenant_flood — a bulk tenant floods the pool at >2x capacity
                     under fair share + per-tenant KV budgets: premium
                     p99 TTFT stays within 1.25x its unloaded
                     baseline, premium sees zero preemptions and zero
                     sheds, bulk sheds carry retry-after hints, and
                     the pool drains to zero with a clean audit.
  llm_drain_sigterm — SIGTERM during live streams: serve_forever
                     drains, every client gets a terminal frame (never
                     a bare reset), KV pool empties, and the process
                     dies with the honest SIGTERM wait status.
  llm_decode_error — an injected decode exception error-terminates
                     exactly ONE sequence; the other finishes with
                     dense parity and every KV block is freed.
  llm_prefix_cow_leak — one of two prefix-sharing streams dies
                     mid-chunked-prefill (llm_chunk_prefill fault,
                     after its copy-on-write fired): the survivor
                     keeps exact dense parity, refcounted blocks are
                     NOT freed while referenced, pool drains to zero.
  llm_flight_deck  — a prefix-sharing stream is preempted MID-prefill,
                     re-COWs at its divergence point on readmission,
                     and rolls back draft windows: its /llm/seqs
                     timeline orders preempted < cow_copy <
                     spec_window{rollback}, serving_report attributes
                     its gaps to those causes with exclusive buckets,
                     and ptlint stays green on the flight-deck code.
  hang_doctor      — an injected decode wedge (faults sleep inside the
                     engine step) is diagnosed LIVE: /stacks serves
                     during the stall, the hang monitor's
                     hang_diagnosis flight event names the injected
                     frame (faults.py:_injected_wedge_sleep), and a
                     postmortem bundle pulled from the wedged process
                     renders a report attributing the stall.
  slo_burn_alert   — an engineered overload (slow prefill fault +
                     admission-watermark flood) burns the
                     serving_availability SLO: the fast multi-window
                     burn-rate alert fires with a flight-recorder
                     transition, resolves once the load stops, and the
                     serving plane comes out with zero KV leak and a
                     clean engine audit.

Usage:
  python tools/chaos_drill.py --self-test        # all drills (CPU)
  python tools/chaos_drill.py --drill kill_mid_save
  python tools/chaos_drill.py --list             # drill inventory
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import shutil
import signal
import subprocess
import sys
import tempfile
import textwrap
import time

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if ROOT not in sys.path:  # runnable from any cwd
    sys.path.insert(0, ROOT)

# Per-step auto-checkpointing trainer driven entirely by env flags;
# writes {"resumed": <step>, "attempt": N} to its output path before
# training so the driver can assert the resume point.
_TRAINER = textwrap.dedent("""
    import json, os, sys
    import numpy as np
    import jax
    jax.config.update("jax_platforms", "cpu")
    import paddle_tpu as pt
    from paddle_tpu import io
    from paddle_tpu.sysconfig import enable_compile_cache

    enable_compile_cache()
    ckdir, outpath = sys.argv[1], sys.argv[2]
    n_steps = int(sys.argv[3]) if len(sys.argv) > 3 else 12

    rng = np.random.default_rng(0)
    batches = [(rng.normal(size=(8, 4)).astype(np.float32),
                rng.integers(0, 2, (8,)).astype(np.int64))
               for _ in range(n_steps)]
    pt.seed(0)
    net = pt.nn.Linear(4, 2)
    model = pt.hapi.Model(
        net, loss=lambda o, y: pt.nn.functional.cross_entropy(o, y),
        optimizer=pt.optimizer.SGD(learning_rate=0.1))
    resumed = io.AsyncCheckpointer(ckdir).latest_step() or 0
    with open(outpath, "w") as f:
        json.dump({"resumed": resumed,
                   "attempt": int(os.environ.get("PT_ELASTIC_ATTEMPT",
                                                 "0"))}, f)
    model.fit(batches, epochs=1, verbose=0, ckpt_dir=ckdir,
              save_steps=2)
    with open(outpath, "w") as f:
        json.dump({"resumed": resumed, "done": True,
                   "attempt": int(os.environ.get("PT_ELASTIC_ATTEMPT",
                                                 "0"))}, f)
""")


class DrillFailure(AssertionError):
    pass


def _check(cond, msg):
    if not cond:
        raise DrillFailure(msg)


def _env(tmp, fault_spec=None):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = ROOT + os.pathsep + env.get("PYTHONPATH", "")
    env["FLAGS_enable_metrics"] = "1"
    env["FLAGS_metrics_port"] = "-1"        # no HTTP exporter in drills
    env["FLAGS_trace_dir"] = os.path.join(tmp, "trace")
    if fault_spec:
        env["FLAGS_fault_spec"] = fault_spec
    else:
        env.pop("FLAGS_fault_spec", None)
    return env


def _run_trainer(tmp, ckdir, fault_spec=None, steps=12, timeout=240):
    script = os.path.join(tmp, "trainer.py")
    if not os.path.exists(script):
        with open(script, "w") as f:
            f.write(_TRAINER)
    out = os.path.join(tmp, "result.json")
    if os.path.exists(out):
        os.remove(out)
    proc = subprocess.run(
        [sys.executable, script, ckdir, out, str(steps)],
        env=_env(tmp, fault_spec), capture_output=True, text=True,
        timeout=timeout)
    result = json.load(open(out)) if os.path.exists(out) else {}
    return proc, result


def _intact_checkpoints(ckdir):
    from paddle_tpu import io
    ck = io.AsyncCheckpointer(ckdir)
    return {s: io.verify(os.path.join(ckdir, f"ckpt-{s}"))
            for s in ck.intact_steps()}


# --------------------------------------------------------------- drills

def drill_kill_mid_save(tmp):
    """SIGKILL fired by the checkpoint writer mid-save of step 8."""
    ck = os.path.join(tmp, "ck_kill")
    p1, _ = _run_trainer(tmp, ck, fault_spec="ckpt_write:step=8:kill=9")
    _check(p1.returncode == -signal.SIGKILL,
           f"expected SIGKILL death, rc={p1.returncode}\n{p1.stderr}")
    from paddle_tpu import io
    latest = io.AsyncCheckpointer(ck).latest_step()
    _check(latest == 6, f"newest intact checkpoint should be 6, "
           f"got {latest} ({sorted(os.listdir(ck))})")
    p2, res = _run_trainer(tmp, ck)
    _check(p2.returncode == 0, f"restart failed rc={p2.returncode}\n"
           f"{p2.stderr}")
    _check(res.get("resumed") == 6 and res.get("done"),
           f"restart should resume from 6 and finish, got {res}")
    reports = _intact_checkpoints(ck)
    _check(reports and all(not v for v in reports.values()),
           f"post-restart checkpoints not intact: {reports}")
    _check(not glob.glob(os.path.join(ck, "*.tmp")),
           "stale .tmp staging dir survived the restart")
    return f"killed mid ckpt-8 write, resumed from 6, finished clean"


def drill_corrupt_leaf(tmp):
    """Bit-flip the newest checkpoint; restore falls back one step."""
    ck = os.path.join(tmp, "ck_corrupt")
    p1, _ = _run_trainer(tmp, ck)
    _check(p1.returncode == 0, f"clean run failed\n{p1.stderr}")
    from paddle_tpu import io
    from paddle_tpu.observability import flight, metrics
    ckptr = io.AsyncCheckpointer(ck)
    steps = ckptr.intact_steps()
    _check(len(steps) >= 2, f"need >=2 checkpoints, got {steps}")
    newest, fallback = steps[-1], steps[-2]
    leaf = sorted(glob.glob(os.path.join(ck, f"ckpt-{newest}",
                                         "data", "*.npy")))[0]
    raw = open(leaf, "rb").read()
    with open(leaf, "wb") as f:       # same size, different bytes
        f.write(raw[:-1] + bytes([raw[-1] ^ 0xFF]))
    _check(io.verify(os.path.join(ck, f"ckpt-{newest}")),
           "verify() missed the corrupted leaf")
    before = metrics.counter("checkpoint_corrupt_total",
                              always=True).value()
    state, got = ckptr.restore_latest()
    _check(got == fallback and state is not None,
           f"restore should fall back to {fallback}, got {got}")
    _check(metrics.counter("checkpoint_corrupt_total",
                           always=True).value()
           == before + 1, "checkpoint_corrupt_total did not increment")
    events = [e for e in flight.recorder().events()
              if e.get("kind") == "checkpoint_corrupt"]
    _check(events, "no checkpoint_corrupt flight event recorded")
    # a stripped COMMIT marker must also be skipped
    os.remove(os.path.join(ck, f"ckpt-{fallback}", "COMMIT"))
    _, got2 = ckptr.restore_latest()
    _check(got2 is not None and got2 < fallback,
           f"uncommitted fallback not skipped, got {got2}")
    return (f"corrupt ckpt-{newest} fell back to {fallback}; "
            f"stripped COMMIT fell back to {got2}; counter+event ok")


def drill_sigterm_mid_fit(tmp):
    """Scheduler preemption at train step 7, resume where it died."""
    ck = os.path.join(tmp, "ck_term")
    p1, _ = _run_trainer(tmp, ck, fault_spec="sigterm:step=7")
    _check(p1.returncode in (-signal.SIGTERM, 128 + signal.SIGTERM),
           f"expected SIGTERM wait status, rc={p1.returncode}\n"
           f"{p1.stderr}")
    from paddle_tpu import io
    latest = io.AsyncCheckpointer(ck).latest_step()
    _check(latest == 8, f"preemption checkpoint should land at 8 "
           f"(step 7 finished), got {latest}")
    dumps = glob.glob(os.path.join(tmp, "trace", "flight_*.jsonl"))
    _check(dumps, "no flight dump written on preemption")
    dump_text = "".join(open(d).read() for d in dumps)
    _check("preemption_notice" in dump_text,
           "flight dump lacks the preemption_notice event")
    _check("preempt_checkpoint" in dump_text,
           "flight dump lacks the preempt_checkpoint event")
    p2, res = _run_trainer(tmp, ck)
    _check(p2.returncode == 0 and res.get("resumed") == 8
           and res.get("done"),
           f"restart should resume from 8 and finish, got "
           f"rc={p2.returncode} {res}")
    return "preempted after step 7, checkpointed at 8, resumed at 8"


def drill_crash_loop(tmp):
    """Deterministic crash at step 3; the restart budget fails fast."""
    from paddle_tpu.distributed.launch import launch_elastic
    ck = os.path.join(tmp, "ck_loop")
    script = os.path.join(tmp, "trainer.py")
    if not os.path.exists(script):
        with open(script, "w") as f:
            f.write(_TRAINER)
    out = os.path.join(tmp, "loop_result.json")
    log = os.path.join(tmp, "loop_attempts.log")
    env = _env(tmp, fault_spec="train_step:step=3:exc=RuntimeError")
    t0 = time.time()
    rc = launch_elastic(
        [sys.executable, script, ck, out, "12"], nproc=1,
        max_restarts=8, env_extra=env, backoff_s=0.05,
        backoff_max_s=0.2, restart_budget=2, restart_window_s=60.0)
    elapsed = time.time() - t0
    _check(rc != 0, "crash loop unexpectedly converged")
    attempts = json.load(open(out)).get("attempt")
    _check(attempts == 2,
           f"budget of 2 should stop after attempts 0,1,2 — last "
           f"attempt was {attempts}")
    from paddle_tpu.observability import metrics
    _check(metrics.counter("elastic_budget_exhausted_total",
                           always=True).value()
           >= 1, "budget-exhausted counter not incremented")
    return (f"crash-loop stopped by budget after 3 attempts "
            f"({elapsed:.1f}s), not max_restarts=8")


# Skip-guard trainer: reports the nonfinite counter + weight health
# so the driver can assert the skips actually happened in-graph.
_NONFINITE_TRAINER = textwrap.dedent("""
    import json, sys
    import numpy as np
    import jax
    jax.config.update("jax_platforms", "cpu")
    import paddle_tpu as pt
    from paddle_tpu.observability import metrics
    from paddle_tpu.sysconfig import enable_compile_cache

    enable_compile_cache()
    outpath = sys.argv[1]
    rng = np.random.default_rng(0)
    batches = [(rng.normal(size=(8, 4)).astype(np.float32),
                rng.integers(0, 2, (8,)).astype(np.int64))
               for _ in range(10)]
    pt.seed(0)
    net = pt.nn.Linear(4, 2)
    model = pt.hapi.Model(
        net, loss=lambda o, y: pt.nn.functional.cross_entropy(o, y),
        optimizer=pt.optimizer.SGD(learning_rate=0.1))
    hist = model.fit(batches, epochs=1, verbose=0)
    jax.effects_barrier()   # drain the async nonfinite-step callbacks
    w = {k: np.asarray(v) for k, v in net.state_dict().items()}
    with open(outpath, "w") as f:
        json.dump({
            "done": True,
            "nonfinite_steps": metrics.counter(
                "nonfinite_steps_total", always=True).value(),
            "weights_finite": bool(all(np.isfinite(a).all()
                                       for a in w.values())),
            "loss_finite": bool(np.isfinite(hist["loss"][-1])),
        }, f)
""")


def drill_nonfinite_skip(tmp):
    """Two injected NaN-gradient steps must be skipped in-graph."""
    script = os.path.join(tmp, "nonfinite_trainer.py")
    with open(script, "w") as f:
        f.write(_NONFINITE_TRAINER)
    out = os.path.join(tmp, "nonfinite_result.json")
    proc = subprocess.run(
        [sys.executable, script, out],
        env=_env(tmp, fault_spec="nonfinite_grad:step=3,"
                                 "nonfinite_grad:step=6"),
        capture_output=True, text=True, timeout=240)
    _check(proc.returncode == 0,
           f"skip-guard trainer died rc={proc.returncode}\n"
           f"{proc.stderr}")
    res = json.load(open(out))
    _check(res.get("done"), f"trainer did not finish: {res}")
    _check(res.get("nonfinite_steps", 0) >= 2,
           f"nonfinite_steps_total should be >= 2, got "
           f"{res.get('nonfinite_steps')}")
    _check(res.get("weights_finite"),
           "weights went non-finite despite the skip guard")
    _check(res.get("loss_finite"), "epoch loss went non-finite")
    return (f"{res['nonfinite_steps']} nonfinite-grad steps skipped "
            "in-graph, weights finite, fit completed")


_STREAM_DISCONNECT = r"""
import json, socket, sys, time
import paddle_tpu as pt
from paddle_tpu import observability as obs
from paddle_tpu.inference import Client, Server
from paddle_tpu.models import GPTLanguageModel
from paddle_tpu.serving_llm import LLMEngine

out = sys.argv[1]
model = GPTLanguageModel()
engine = LLMEngine(model, block_size=4, pool_blocks=32)
srv = Server(None, llm_engine=engine)
cli = Client(port=srv.port, timeout_s=60.0)
# ask for far more tokens than we will read, then vanish mid-stream
gen = cli.generate_stream([7] * 9, max_new_tokens=200)
got = [int(next(gen)[0]) for _ in range(2)]
used_mid = engine.allocator.num_used
cli._sock.close()                     # abrupt close, no goodbye frame
# server notices on its next chunk write (rc=-3) and cancels the
# sequence; give it a bounded window to quiesce
deadline = time.time() + 60
while engine.active() and time.time() < deadline:
    time.sleep(0.1)
leak_check = True
try:
    engine.allocator.check()
except AssertionError:
    leak_check = False
res = {
    "tokens_read": len(got),
    "used_mid_stream": used_mid,
    "active_after": engine.active(),
    "kv_used_after": engine.allocator.num_used,
    "kv_used_gauge": obs.gauge("kv_blocks_used").value(),
    "kv_freed_total": engine.allocator.freed_total,
    "allocator_check_ok": leak_check,
    "cancelled_total": obs.counter(
        "serving_stream_cancelled_total").value(),
    "shed_total": (obs.counter("requests_shed_total").total(kind="stream")
                   + obs.counter("requests_shed_total").total(kind="tensor")),
    "flight_cancel_events": sum(
        1 for e in obs.flight.recorder().events()
        if e.get("kind") == "serving_stream_cancelled"),
}
srv.stop()
json.dump(res, open(out, "w"))
"""


def drill_stream_disconnect(tmp):
    """Streaming client vanishes mid-generation: the serving loop must
    cancel the sequence and return every KV block to the pool — no
    leak, and the disconnect is a *cancel*, never a *shed*."""
    script = os.path.join(tmp, "stream_disconnect.py")
    with open(script, "w") as f:
        f.write(_STREAM_DISCONNECT)
    out = os.path.join(tmp, "stream_disconnect.json")
    proc = subprocess.run(
        [sys.executable, script, out], env=_env(tmp),
        capture_output=True, text=True, timeout=240)
    _check(proc.returncode == 0,
           f"stream-disconnect run died rc={proc.returncode}\n"
           f"{proc.stderr}")
    res = json.load(open(out))
    _check(res["tokens_read"] == 2 and res["used_mid_stream"] > 0,
           f"stream never got going: {res}")
    _check(not res["active_after"],
           f"engine still active after disconnect: {res}")
    _check(res["kv_used_after"] == 0 and res["kv_used_gauge"] == 0.0,
           f"KV blocks leaked after disconnect: {res}")
    _check(res["allocator_check_ok"],
           f"allocator invariant audit failed: {res}")
    _check(res["cancelled_total"] >= 1,
           f"serving_stream_cancelled_total not counted: {res}")
    _check(res["flight_cancel_events"] >= 1,
           f"no serving_stream_cancelled flight event: {res}")
    _check(res["shed_total"] == 0,
           f"disconnect was miscounted as a shed: {res}")
    return (f"client vanished after {res['tokens_read']} tokens; "
            f"{res['kv_freed_total']} KV blocks freed, pool clean, "
            f"cancel counted (sheds untouched)")


_LLM_OVERLOAD = r"""
import json, sys, threading
import numpy as np
import paddle_tpu as pt
from paddle_tpu import observability as obs
from paddle_tpu.inference import Client, Server
from paddle_tpu.models import GPTLanguageModel
from paddle_tpu.serving_llm import LLMEngine

out = sys.argv[1]
model = GPTLanguageModel()
# 8-block pool, watermark 0.5 -> admission budget of 4 blocks; each
# request projects ceil((5 prompt + 6 new)/4) = 3 blocks, so only one
# fits at a time and a 6-client flood MUST see rejections
engine = LLMEngine(model, block_size=4, pool_blocks=8)
srv = Server(None, llm_engine=engine)
PROMPT = [5, 6, 7, 8, 9]
results = []
lock = threading.Lock()

def worker(i):
    cli = Client(port=srv.port, timeout_s=120.0)
    try:
        toks = cli.generate(PROMPT, max_new_tokens=6, retry=False)
        with lock:
            results.append(("ok", [int(t) for t in toks]))
    except RuntimeError as e:
        with lock:
            results.append(("rejected", str(e)))
    finally:
        cli.close()

threads = [threading.Thread(target=worker, args=(i,)) for i in range(6)]
for t in threads:
    t.start()
for t in threads:
    t.join()
# parity reference: the same prompt on the now-idle server (greedy
# decode is batch-independent, so admitted-under-load == solo)
cli = Client(port=srv.port, timeout_s=120.0)
ref = [int(t) for t in cli.generate(PROMPT, max_new_tokens=6)]
cli.close()
ok = [r for r in results if r[0] == "ok"]
rej = [r for r in results if r[0] == "rejected"]
res = {
    "n_ok": len(ok),
    "n_rejected": len(rej),
    "parity": all(r[1] == ref for r in ok),
    "hints": all("retry_after_ms=" in r[1] for r in rej),
    "admission_rejected_total": obs.counter(
        "llm_admission_rejected_total").total(),
    "preempted_total": obs.counter("kv_blocks_preempted_total").total(),
    "kv_used_after": engine.allocator.num_used,
}
srv.stop()
json.dump(res, open(out, "w"))
"""


def drill_llm_overload_shed(tmp):
    """Stream flood past the KV watermark: extras rejected at
    admission with a retry-after hint, zero preemption thrash,
    admitted streams keep exact parity, pool drains to zero."""
    script = os.path.join(tmp, "llm_overload.py")
    with open(script, "w") as f:
        f.write(_LLM_OVERLOAD)
    out = os.path.join(tmp, "llm_overload.json")
    env = _env(tmp)
    env["FLAGS_kv_admission_watermark"] = "0.5"
    proc = subprocess.run(
        [sys.executable, script, out], env=env,
        capture_output=True, text=True, timeout=300)
    _check(proc.returncode == 0,
           f"overload run died rc={proc.returncode}\n{proc.stderr}")
    res = json.load(open(out))
    _check(res["n_ok"] >= 1 and res["n_rejected"] >= 1
           and res["n_ok"] + res["n_rejected"] == 6,
           f"flood should split into admitted + rejected: {res}")
    _check(res["hints"],
           f"rejection payloads lack the retry_after_ms hint: {res}")
    _check(res["admission_rejected_total"] == res["n_rejected"],
           f"llm_admission_rejected_total disagrees with client "
           f"rejections: {res}")
    _check(res["preempted_total"] == 0,
           f"watermark admission must prevent preemption thrash: {res}")
    _check(res["parity"],
           f"admitted-under-load output diverged from solo run: {res}")
    _check(res["kv_used_after"] == 0,
           f"KV blocks leaked after the flood: {res}")
    return (f"{res['n_rejected']} of 6 refused at admission with "
            f"retry hints, 0 preemptions, {res['n_ok']} admitted with "
            f"exact parity, pool drained")


_LLM_TENANT_FLOOD = r"""
import json, sys, threading, time
import numpy as np
import paddle_tpu as pt
from paddle_tpu import observability as obs
from paddle_tpu.inference import Client, Server
from paddle_tpu.models import GPTLanguageModel
from paddle_tpu.serving_llm import LLMEngine
from paddle_tpu.sysconfig import enable_compile_cache

enable_compile_cache()
out = sys.argv[1]
model = GPTLanguageModel()
# 16-block pool: bulk is budget-capped at 8 blocks (tenant_kv_budget
# bulk=0.5) and each bulk request projects ceil((5+6)/4)=3 blocks, so
# at most 2 bulk streams are ever resident -- a sustained 12-worker
# flood is >2x what the whole pool could hold and most of it MUST
# shed, while premium admits into the reserved headroom
engine = LLMEngine(model, block_size=4, pool_blocks=16)
srv = Server(None, llm_engine=engine)
B_PROMPT = [5, 6, 7, 8, 9]
P_PROMPT = list(range(3, 27))   # long prompt: TTFT is prefill-bound

def premium_ttft(cli):
    t0 = time.monotonic()
    gen = cli.generate_stream(P_PROMPT, max_new_tokens=4,
                              temperature=0.0, tenant="prem",
                              priority_class="premium")
    toks = [int(t) for t in np.asarray(next(gen)).ravel()]
    dt = time.monotonic() - t0
    for ch in gen:
        toks.extend(int(t) for t in np.asarray(ch).ravel())
    return dt, toks

bulk_results = []
lock = threading.Lock()

def start_flood(record):
    stop = threading.Event()

    def bulk_worker(i):
        c = Client(port=srv.port, timeout_s=120.0)
        try:
            while not stop.is_set():
                try:
                    toks = c.generate(B_PROMPT, max_new_tokens=6,
                                      retry=False, tenant="bulk",
                                      priority_class="bulk")
                    if record:
                        with lock:
                            bulk_results.append(
                                ("ok", [int(t) for t in toks]))
                except RuntimeError as e:
                    if record:
                        with lock:
                            bulk_results.append(("rejected", str(e)))
                    time.sleep(0.05)    # honor the backoff hint
        finally:
            c.close()

    threads = [threading.Thread(target=bulk_worker, args=(i,))
               for i in range(12)]
    for t in threads:
        t.start()
    return stop, threads

def stop_flood(stop, threads):
    stop.set()
    for t in threads:
        t.join()
    deadline = time.monotonic() + 10.0
    while engine.allocator.num_used and time.monotonic() < deadline:
        time.sleep(0.05)

cli = Client(port=srv.port, timeout_s=120.0)
# warm EVERY shape the measurement will hit — solo premium AND
# premium prefill riding a resident bulk decode batch — so the
# loaded phase never pays a first-composition XLA compile
premium_ttft(cli)
stop, threads = start_flood(record=False)
time.sleep(0.3)
for _ in range(2):
    premium_ttft(cli)
stop_flood(stop, threads)

ref = None
baseline = []
for _ in range(8):
    dt, toks = premium_ttft(cli)
    baseline.append(dt)
    ref = toks if ref is None else ref

stop, threads = start_flood(record=True)
time.sleep(0.3)                         # flood reaches steady state
loaded, parity, premium_errors = [], True, 0
for _ in range(8):
    try:
        dt, toks = premium_ttft(cli)
        loaded.append(dt)
        parity = parity and toks == ref
    except RuntimeError:
        premium_errors += 1
stop_flood(stop, threads)
cli.close()
try:
    engine.allocator.check()
    audit_ok = True
except AssertionError:
    audit_ok = False
ok = [r for r in bulk_results if r[0] == "ok"]
rej = [r for r in bulk_results if r[0] == "rejected"]
res = {
    "baseline_p99_ms": max(baseline) * 1e3,
    "loaded_p99_ms": max(loaded) * 1e3 if loaded else -1.0,
    # floor the baseline at 100ms before the ratio: on CPU the
    # unloaded TTFT is a few tens of ms of interpreter overhead, so a
    # raw ratio would amplify GIL jitter into flakes. The floored
    # check degenerates to "premium p99 <= 125ms absolute" — still an
    # order of magnitude under what a starved premium shows (seconds,
    # queued behind the bulk backlog)
    "ttft_ratio": (max(loaded) / max(max(baseline), 0.10))
                  if loaded else -1.0,
    "premium_errors": premium_errors,
    "premium_parity": parity,
    "premium_preempted": obs.counter(
        "kv_blocks_preempted_total").value(**{"class": "premium"}),
    "premium_rejected": obs.counter(
        "llm_admission_rejected_total").total(tenant="prem"),
    "premium_shed": obs.counter(
        "requests_shed_total").total(tenant="prem"),
    "n_bulk_ok": len(ok),
    "n_bulk_rejected": len(rej),
    "bulk_hints": all("retry_after_ms=" in r[1] for r in rej),
    "bulk_rejected_total": obs.counter(
        "llm_admission_rejected_total").total(tenant="bulk"),
    "kv_used_after": engine.allocator.num_used,
    "audit_ok": audit_ok,
}
srv.stop()
json.dump(res, open(out, "w"))
"""


def drill_llm_tenant_flood(tmp):
    """Bulk tenant floods the pool at >2x capacity while premium
    keeps flowing: premium p99 TTFT stays within 1.25x its unloaded
    baseline, premium is never preempted or shed, bulk sheds carry
    retry hints, and the pool drains clean."""
    script = os.path.join(tmp, "llm_tenant_flood.py")
    with open(script, "w") as f:
        f.write(_LLM_TENANT_FLOOD)
    out = os.path.join(tmp, "llm_tenant_flood.json")
    env = _env(tmp)
    env["FLAGS_tenant_fair_share"] = "1"
    env["FLAGS_tenant_weights"] = "prem=10,bulk=1"
    env["FLAGS_tenant_kv_budget"] = "bulk=0.5"
    env["FLAGS_kv_admission_watermark"] = "0.9"
    proc = subprocess.run(
        [sys.executable, script, out], env=env,
        capture_output=True, text=True, timeout=300)
    _check(proc.returncode == 0,
           f"tenant flood run died rc={proc.returncode}\n{proc.stderr}")
    res = json.load(open(out))
    _check(res["n_bulk_rejected"] >= 4,
           f"a >2x-capacity bulk flood should shed most of its wave: "
           f"{res}")
    _check(res["bulk_hints"],
           f"bulk rejection payloads lack the retry_after_ms hint: "
           f"{res}")
    _check(res["bulk_rejected_total"] >= res["n_bulk_rejected"],
           f"llm_admission_rejected_total{{tenant=bulk}} disagrees "
           f"with bulk client rejections: {res}")
    _check(res["premium_errors"] == 0 and res["premium_rejected"] == 0
           and res["premium_shed"] == 0,
           f"premium must never be rejected or shed under bulk load: "
           f"{res}")
    _check(res["premium_preempted"] == 0,
           f"premium KV blocks were preempted under bulk load: {res}")
    _check(res["ttft_ratio"] <= 1.25,
           f"premium p99 TTFT degraded past 1.25x the unloaded "
           f"baseline: {res}")
    _check(res["premium_parity"],
           f"premium output under load diverged from the unloaded "
           f"reference: {res}")
    _check(res["kv_used_after"] == 0,
           f"KV blocks leaked after the flood: {res}")
    _check(res["audit_ok"], f"allocator audit failed: {res}")
    return (f"premium TTFT {res['loaded_p99_ms']:.0f}ms vs "
            f"{res['baseline_p99_ms']:.0f}ms unloaded "
            f"(ratio {res['ttft_ratio']:.2f}), 0 premium "
            f"preemptions/sheds, {res['n_bulk_rejected']} bulk "
            f"sheds with hints, pool drained")


_SLO_BURN = r"""
import json, sys, threading, time
import paddle_tpu as pt
from paddle_tpu import observability as obs
from paddle_tpu.inference import Client, Server
from paddle_tpu.models import GPTLanguageModel
from paddle_tpu.observability import slo as slo_mod
from paddle_tpu.observability import tsdb as tsdb_mod
from paddle_tpu.serving_llm import LLMEngine
from paddle_tpu.sysconfig import enable_compile_cache

enable_compile_cache()
out = sys.argv[1]
# scaled windows: fast pair 3s/36s @ 14.4, slow pair 18s/216s @ 6 —
# the production burn arithmetic, compressed into drill seconds
pt.set_flags({"slo_window_scale": 0.01, "tsdb_interval_s": 0.1,
              "kv_admission_watermark": 0.0, "fault_spec": ""})
slo_mod.ensure_default_pack()
eng = slo_mod.engine()

def alert():
    return {a["slo"]: a for a in eng.evaluate()}["serving_availability"]

model = GPTLanguageModel()
# 8-block pool + 0.5 watermark (armed below): budget 4 blocks, each
# request projects 3, so a 6-client wave MUST see rejections (burn)
engine = LLMEngine(model, block_size=4, pool_blocks=8)
srv = Server(None, llm_engine=engine)
PROMPT = [5, 6, 7, 8, 9]

def wave(n):
    def worker():
        cli = Client(port=srv.port, timeout_s=120.0)
        try:
            cli.generate(PROMPT, max_new_tokens=4, retry=False)
        except RuntimeError:
            pass
        finally:
            cli.close()
    ts = [threading.Thread(target=worker) for _ in range(n)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()

wave(1)  # jit warm-up lands inside the first tsdb sample (baseline)
tsdb_mod.start()
time.sleep(0.4)
baseline = alert()["state"]

# overload: slow prefill + watermark flood -> availability burns fast
pt.set_flags({"kv_admission_watermark": 0.5,
              "fault_spec": "llm_prefill:sleep=1200"})
fired = False
fast_over = False
fast_burn = 0.0
deadline = time.monotonic() + 120.0
while time.monotonic() < deadline and not fired:
    wave(6)
    a = alert()
    if a["state"] == "firing":
        fired = True
        fast_over = a["windows"]["fast"]["over"]
        fast_burn = a["windows"]["fast"]["short"]["burn_rate"]

# load stops: the short windows drain and the alert must resolve.
# Shrink the scale further so the slow pair's windows age out the
# rejection burst in CI seconds instead of 18 drill-seconds.
pt.set_flags({"fault_spec": "", "kv_admission_watermark": 0.0,
              "slo_window_scale": 0.002})
resolved = False
deadline = time.monotonic() + 90.0
while time.monotonic() < deadline:
    if alert()["state"] != "firing":
        resolved = True
        break
    time.sleep(0.25)

tsdb_mod.stop()
srv.stop()
ev = [e for e in obs.flight.recorder().events()
      if e.get("kind") == "slo_alert"
      and e.get("slo") == "serving_availability"]
hist = [t["to"] for a in eng.alerts_view()["alerts"]
        if a["slo"] == "serving_availability" for t in a["history"]]
audit_ok = True
try:
    engine.allocator.check()
    engine._audit()
except Exception:
    audit_ok = False
res = {
    "baseline": baseline,
    "fired": fired,
    "fast_over": fast_over,
    "fast_burn": fast_burn,
    "resolved": resolved,
    "history": hist,
    "flight_firing": sum(1 for e in ev if e["to_state"] == "firing"),
    "flight_resolved": sum(1 for e in ev if e["to_state"] == "resolved"),
    "rejected_total": obs.counter(
        "llm_admission_rejected_total").total(),
    "kv_used_after": engine.allocator.num_used,
    "audit_ok": audit_ok,
}
json.dump(res, open(out, "w"))
"""


def drill_slo_burn_alert(tmp):
    """Engineered overload burns the availability SLO: the fast
    multi-window burn-rate alert fires (both windows over the page
    threshold) with a flight-recorder transition, then resolves after
    the load stops — and the serving plane comes out clean (zero KV
    leak, engine audit passes)."""
    script = os.path.join(tmp, "slo_burn.py")
    with open(script, "w") as f:
        f.write(_SLO_BURN)
    out = os.path.join(tmp, "slo_burn.json")
    proc = subprocess.run(
        [sys.executable, script, out], env=_env(tmp),
        capture_output=True, text=True, timeout=420)
    _check(proc.returncode == 0,
           f"slo-burn run died rc={proc.returncode}\n{proc.stderr}")
    res = json.load(open(out))
    _check(res["baseline"] != "firing",
           f"availability alert already firing before overload: {res}")
    _check(res["fired"],
           f"overload never tripped serving_availability: {res}")
    _check(res["fast_over"] and res["fast_burn"] > 14.4,
           f"firing without the fast pair over the page threshold: "
           f"{res}")
    _check(res["rejected_total"] >= 1,
           f"flood produced no admission rejections (nothing burned): "
           f"{res}")
    _check(res["resolved"],
           f"alert never left firing after the load stopped: {res}")
    _check("firing" in res["history"] and "resolved" in res["history"],
           f"state-machine history is missing transitions: {res}")
    _check(res["flight_firing"] >= 1 and res["flight_resolved"] >= 1,
           f"slo_alert flight events missing: {res}")
    _check(res["kv_used_after"] == 0,
           f"KV blocks leaked across the overload: {res}")
    _check(res["audit_ok"],
           f"allocator/engine audit failed after the drill: {res}")
    return (f"availability burned at {res['fast_burn']:.0f}x budget "
            f"(fast pair over 14.4), flight-recorded, resolved after "
            f"load stopped; pool clean")


_HANG_DOCTOR = r"""
import json, os, subprocess, sys, threading, time, urllib.request
import paddle_tpu as pt
from paddle_tpu import observability as obs
from paddle_tpu.models import GPTLanguageModel
from paddle_tpu.observability import server as obs_server
from paddle_tpu.observability import stacks as stacks_mod
from paddle_tpu.serving_llm import LLMEngine
from paddle_tpu.sysconfig import enable_compile_cache

enable_compile_cache()
out, bundle, root = sys.argv[1], sys.argv[2], sys.argv[3]
pt.set_flags({"enable_metrics": True, "stack_sample_hz": 50.0,
              "hang_check_interval_s": 0.1, "llm_stall_factor": 4.0,
              "fault_spec": ""})
srv = obs_server.start(0)
stacks_mod.maybe_start()  # sampler + hang monitor + SIGUSR2 dump
base = "http://127.0.0.1:%d" % srv.port

model = GPTLanguageModel()
engine = LLMEngine(model, block_size=4, pool_blocks=64)

# baseline: identical requests so run 2+ reuse run 1's compiled
# shapes — the step-time EWMA the live stall judgement compares
# against must reflect warm steps, not jit compiles
for _ in range(3):
    engine.add_request([5, 6, 7], max_new_tokens=8)
    while engine.active():
        engine.step()

# wedge: the 3rd decode hit of the NEXT request parks inside
# faults._injected_wedge_sleep for 3s — a live, diagnosable stall
pt.set_flags({"fault_spec": "llm_decode:sleep=3000:at=3"})
engine.add_request([5, 6, 7], max_new_tokens=8)

def step_loop():
    while engine.active():
        engine.step()

stepper = threading.Thread(target=step_loop, name="llm-stepper",
                           daemon=False)

stacks_codes = []
wedged_rec = None
healthz_stalled = False

def http_json(path):
    # /healthz answers 503 while the engine is stalled — that IS the
    # signal, so read HTTPError bodies instead of treating them as
    # connection failures
    try:
        with urllib.request.urlopen(base + path, timeout=5) as r:
            return r.status, json.loads(r.read().decode())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read().decode())

stepper.start()
deadline = time.monotonic() + 60.0
while stepper.is_alive() and time.monotonic() < deadline:
    try:
        code, view = http_json("/stacks?n=16")
    except Exception:
        time.sleep(0.05)
        continue
    stacks_codes.append(code)
    for t in view.get("threads", []):
        if t["name"] == "llm-stepper" and any(
                "_injected_wedge_sleep" in f for f in t["frames"]):
            if wedged_rec is None:
                wedged_rec = t
            try:
                _, h = http_json("/healthz")
                for e in (h.get("serving") or {}).get("engines", []):
                    healthz_stalled = healthz_stalled or e["stalled"]
            except Exception:
                pass
    time.sleep(0.05)
stepper.join(60.0)

# the monitor diagnoses DURING the wedge; give its 0.1s tick a beat
diag = None
deadline = time.monotonic() + 10.0
while diag is None and time.monotonic() < deadline:
    evs = [e for e in obs.flight.recorder().events()
           if e.get("kind") == "hang_diagnosis"
           and e.get("source") == "serving"]
    diag = evs[-1] if evs else None
    time.sleep(0.1)

# operator flow: postmortem bundle pulled from the live process, then
# rendered offline — the report must attribute the stall by itself
pm = os.path.join(root, "tools", "postmortem.py")
env = dict(os.environ); env["JAX_PLATFORMS"] = "cpu"
collect = subprocess.run(
    [sys.executable, pm, "collect", "--url", base, "--out", bundle],
    capture_output=True, text=True, timeout=120, env=env)
render = subprocess.run(
    [sys.executable, pm, "render", bundle],
    capture_output=True, text=True, timeout=120, env=env)

status = stacks_mod.sampler().status()
audit_ok = True
try:
    engine.allocator.check()
    engine._audit()
except Exception:
    audit_ok = False
res = {
    "stacks_codes": sorted(set(stacks_codes)),
    "n_polls": len(stacks_codes),
    "wedged_state": (wedged_rec or {}).get("state"),
    "wedged_frames": (wedged_rec or {}).get("frames", []),
    "healthz_stalled": healthz_stalled,
    "diagnosis": diag,
    "stalls_total": engine.stalls_total,
    "collect_rc": collect.returncode,
    "collect_err": collect.stderr[-800:],
    "render_rc": render.returncode,
    "render_out": render.stdout,
    "overhead_ratio": status.get("overhead_ratio"),
    "samples": status.get("samples"),
    "kv_used_after": engine.allocator.num_used,
    "audit_ok": audit_ok,
}
srv.stop()
json.dump(res, open(out, "w"))
"""


def drill_hang_doctor(tmp):
    """An injected decode wedge (faults sleep inside the engine step)
    is caught LIVE: /stacks serves during the stall, the hang monitor
    records a hang_diagnosis flight event whose culprit frame names
    faults.py:_injected_wedge_sleep, and a postmortem bundle pulled
    from the wedged process renders a report attributing the stall."""
    script = os.path.join(tmp, "hang_doctor.py")
    with open(script, "w") as f:
        f.write(_HANG_DOCTOR)
    out = os.path.join(tmp, "hang_doctor.json")
    bundle = os.path.join(tmp, "hang_bundle")
    proc = subprocess.run(
        [sys.executable, script, out, bundle, ROOT], env=_env(tmp),
        capture_output=True, text=True, timeout=420)
    _check(proc.returncode == 0,
           f"hang-doctor run died rc={proc.returncode}\n{proc.stderr}")
    res = json.load(open(out))
    _check(res["stacks_codes"] == [200] and res["n_polls"] >= 1,
           f"/stacks did not serve 200 during the wedge: {res}")
    _check(res["wedged_state"] == "blocked_in_io",
           f"wedged stepper not classified blocked_in_io: "
           f"{res['wedged_state']} {res['wedged_frames']}")
    _check(any("_injected_wedge_sleep" in fr
               for fr in res["wedged_frames"]),
           f"live /stacks never showed the injected frame: {res}")
    _check(res["healthz_stalled"],
           f"/healthz never reported the engine stalled mid-wedge: "
           f"{res}")
    diag = res["diagnosis"]
    _check(diag is not None,
           f"no hang_diagnosis flight event from the monitor: {res}")
    culprit = diag.get("culprit") or {}
    _check(culprit.get("thread") == "llm-stepper",
           f"diagnosis blamed the wrong thread: {culprit}")
    _check(any("_injected_wedge_sleep" in fr
               for fr in culprit.get("frames", [])),
           f"diagnosis culprit does not name the injected frame: "
           f"{culprit}")
    _check(res["collect_rc"] == 0,
           f"postmortem collect failed: {res['collect_err']}")
    _check(res["render_rc"] == 0 and "CULPRIT" in res["render_out"]
           and "_injected_wedge_sleep" in res["render_out"],
           f"postmortem render did not attribute the stall:\n"
           f"{res['render_out'][:2000]}")
    _check(res["overhead_ratio"] is not None
           and res["overhead_ratio"] < 0.02,
           f"sampler overhead {res['overhead_ratio']} >= 2%: {res}")
    _check(res["kv_used_after"] == 0 and res["audit_ok"],
           f"engine came out dirty after the wedge: {res}")
    return (f"live wedge diagnosed (culprit "
            f"{culprit.get('frame')}), /stacks 200 x{res['n_polls']} "
            f"during stall, postmortem report attributes it, sampler "
            f"overhead {res['overhead_ratio']:.1%}")


_LLM_DRAIN_SERVER = r"""
import json, sys
import paddle_tpu as pt
from paddle_tpu.inference import Server
from paddle_tpu.models import GPTLanguageModel
from paddle_tpu.serving_llm import LLMEngine

out, portfile = sys.argv[1], sys.argv[2]
model = GPTLanguageModel()
# pool sized so 4 concurrent 209-token streams fit WITHOUT
# preemption (4 x 53 blocks) — the drill measures drain behaviour,
# not pool contention, and a starved stream would stall the driver
engine = LLMEngine(model, block_size=4, pool_blocks=256)
srv = Server(None, llm_engine=engine)

def on_drained(server):
    ok = True
    try:
        engine.allocator.check()
    except AssertionError:
        ok = False
    json.dump({"kv_used": engine.allocator.num_used,
               "check_ok": ok,
               "open_streams": len(server._llm._reqs)},
              open(out, "w"))

with open(portfile, "w") as f:
    f.write(str(srv.port))
srv.serve_forever(on_drained=on_drained)
"""


def drill_llm_drain_sigterm(tmp):
    """SIGTERM with 4 live streams: drain gives every client a
    terminal frame (finish or explicit drain error, never a bare
    reset), empties the KV pool, and exits with the SIGTERM status."""
    import threading
    from paddle_tpu.inference import Client
    script = os.path.join(tmp, "llm_drain_server.py")
    with open(script, "w") as f:
        f.write(_LLM_DRAIN_SERVER)
    out = os.path.join(tmp, "llm_drain_state.json")
    portfile = os.path.join(tmp, "llm_drain_port.txt")
    for p in (out, portfile):
        if os.path.exists(p):
            os.remove(p)
    env = _env(tmp)
    env["FLAGS_serving_drain_deadline_s"] = "1.0"
    proc = subprocess.Popen(
        [sys.executable, script, out, portfile], env=env,
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True)
    try:
        deadline = time.time() + 120
        while not os.path.exists(portfile) and time.time() < deadline:
            if proc.poll() is not None:  # communicate() only on death
                raise DrillFailure(
                    f"drain server died during startup\n"
                    f"{proc.communicate()[1]}")
            time.sleep(0.1)
        _check(os.path.exists(portfile), "drain server never bound")
        port = int(open(portfile).read())

        outcomes, started = [], []
        lock = threading.Lock()

        def worker():
            ev = threading.Event()
            with lock:
                started.append(ev)
            cli = Client(port=port, timeout_s=120.0)
            try:
                gen = cli.generate_stream([3, 4, 5] * 3,
                                          max_new_tokens=200)
                for _ in range(2):
                    next(gen)
                ev.set()
                for _ in gen:
                    pass
                outcome = ("finished", "")
            except RuntimeError as e:
                outcome = ("drain" if "drain" in str(e) else "error",
                           str(e))
            except Exception as e:  # noqa: BLE001
                outcome = (type(e).__name__, str(e))
            finally:
                ev.set()
                cli.close()
            with lock:
                outcomes.append(outcome)

        threads = [threading.Thread(target=worker) for _ in range(4)]
        for t in threads:
            t.start()
        deadline = time.time() + 120
        while time.time() < deadline:
            with lock:
                if len(started) == 4 and all(e.is_set()
                                             for e in started):
                    break
            time.sleep(0.05)
        proc.send_signal(signal.SIGTERM)
        for t in threads:
            t.join(timeout=120)
        rc = proc.wait(timeout=120)
        err = proc.stderr.read()
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait()
    _check(rc == -signal.SIGTERM,
           f"drained server must die with the SIGTERM wait status, "
           f"rc={rc}\n{err}")
    _check(len(outcomes) == 4 and all(o[0] in ("finished", "drain")
                                      for o in outcomes),
           f"every client must see a terminal frame, got {outcomes}")
    _check(os.path.exists(out), "on_drained state never written")
    state = json.load(open(out))
    _check(state["kv_used"] == 0 and state["check_ok"]
           and state["open_streams"] == 0,
           f"pool not clean after drain: {state}")
    n_drain = sum(1 for o in outcomes if o[0] == "drain")
    return (f"4 streams: {4 - n_drain} finished, {n_drain} got drain "
            f"frames; pool empty, exit status honest (SIGTERM)")


_LLM_DECODE_ERROR = r"""
import json, sys
import numpy as np
import paddle_tpu as pt
from paddle_tpu import observability as obs
from paddle_tpu.models import GPTLanguageModel
from paddle_tpu.serving_llm import LLMEngine

out = sys.argv[1]
model = GPTLanguageModel()
engine = LLMEngine(model, block_size=4, pool_blocks=32)
prompts = {"a": [1, 2, 3, 4, 5], "b": [7, 8, 9]}
ids = {k: engine.add_request(np.asarray(p, np.int32),
                             max_new_tokens=8, temperature=0.0, seed=0)
       for k, p in prompts.items()}
events = []
for _ in range(64):
    events.extend(engine.step())
    if not engine.active():
        break
errors = [e for e in events if e["type"] == "error"]
finished = [e for e in events if e["type"] == "finished"]
toks = {}
for e in events:
    if e["type"] == "token":
        toks.setdefault(e["seq_id"], []).append(int(e["token"]))
ref_match = None
if len(finished) == 1:
    sid = finished[0]["seq_id"]
    name = [k for k, v in ids.items() if v == sid][0]
    # the at=5 fault already fired, so a fresh engine decodes clean
    eng2 = LLMEngine(model, block_size=4, pool_blocks=32)
    eng2.add_request(np.asarray(prompts[name], np.int32),
                     max_new_tokens=8, temperature=0.0, seed=0)
    ref = []
    for _ in range(64):
        for e in eng2.step():
            if e["type"] == "token":
                ref.append(int(e["token"]))
        if not eng2.active():
            break
    ref_match = toks.get(sid, []) == ref
check_ok = True
try:
    engine.allocator.check()
except AssertionError:
    check_ok = False
res = {
    "n_error": len(errors),
    "n_finished": len(finished),
    "error_msgs": [e["error"] for e in errors],
    "ref_match": ref_match,
    "kv_used_after": engine.allocator.num_used,
    "check_ok": check_ok,
    "faults_injected": obs.counter(
        "faults_injected_total").value(point="llm_decode"),
}
json.dump(res, open(out, "w"))
"""


def drill_llm_decode_error(tmp):
    """Injected decode exception: exactly one sequence error-
    terminates, the other finishes with dense parity, blocks freed."""
    script = os.path.join(tmp, "llm_decode_error.py")
    with open(script, "w") as f:
        f.write(_LLM_DECODE_ERROR)
    out = os.path.join(tmp, "llm_decode_error.json")
    proc = subprocess.run(
        [sys.executable, script, out],
        env=_env(tmp, fault_spec="llm_decode:at=5:exc=RuntimeError"),
        capture_output=True, text=True, timeout=300)
    _check(proc.returncode == 0,
           f"decode-error run died rc={proc.returncode}\n{proc.stderr}")
    res = json.load(open(out))
    _check(res["n_error"] == 1 and res["n_finished"] == 1,
           f"exactly one sequence should fail, one finish: {res}")
    _check(any("fault injected" in m for m in res["error_msgs"]),
           f"error event does not carry the injected fault: {res}")
    _check(res["faults_injected"] == 1,
           f"faults_injected_total{{point=llm_decode}} should be 1: "
           f"{res}")
    _check(res["ref_match"],
           f"survivor diverged from the clean reference: {res}")
    _check(res["kv_used_after"] == 0 and res["check_ok"],
           f"KV blocks leaked after the decode error: {res}")
    return ("decode fault killed one of two sequences; survivor kept "
            "exact parity, all KV blocks freed")


_LLM_PREFIX_COW_LEAK = r"""
import json, sys
import numpy as np
import jax.numpy as jnp
import paddle_tpu as pt
from paddle_tpu import observability as obs
from paddle_tpu.models import GPTLanguageModel
from paddle_tpu.serving_llm import LLMEngine

out = sys.argv[1]
pt.set_flags({"kv_prefix_sharing": True, "prefill_chunk_tokens": 8})
model = GPTLanguageModel()
engine = LLMEngine(model, block_size=4, pool_blocks=32)
shared = list(range(1, 15))              # 14 tokens: 3.5 blocks
prompt_a = shared + [20, 21]             # 16 tokens
prompt_b = shared + list(range(30, 41))  # 25 tokens, diverges at 14
sid_a = engine.add_request(np.asarray(prompt_a, np.int32),
                           max_new_tokens=8)
toks, errors = {}, []
max_shared = 0
used_after_error = check_after_error = None
sid_b = None
for step in range(64):
    if step == 3:
        # A is decoded past its prompt: B admits sharing 3 full
        # blocks + a partial tail of A's block 3 (COW material)
        sid_b = engine.add_request(np.asarray(prompt_b, np.int32),
                                   max_new_tokens=8)
    for e in engine.step():
        if e["type"] == "token":
            toks.setdefault(e["seq_id"], []).append(int(e["token"]))
        elif e["type"] == "error":
            errors.append(e)
            used_after_error = engine.allocator.num_used
            try:
                engine.allocator.check()
                check_after_error = True
            except AssertionError:
                check_after_error = False
    max_shared = max(max_shared, engine.allocator.num_shared)
    if not engine.active():
        break
ref = [int(t) for t in np.asarray(model.generate(
    jnp.asarray([prompt_a], jnp.int32), max_new_tokens=8))[0]]
check_ok = True
try:
    engine.allocator.check()
except AssertionError:
    check_ok = False
res = {
    "n_error": len(errors),
    "error_seq": errors[0]["seq_id"] if errors else None,
    "error_msgs": [e["error"] for e in errors],
    "sid_a": sid_a, "sid_b": sid_b,
    "a_tokens": toks.get(sid_a, []),
    "dense_ref": ref,
    "max_shared": max_shared,
    "cow_copies": engine.allocator.cow_copies_total,
    "prefix_hits": engine.allocator.prefix_hit_tokens_total,
    "used_after_error": used_after_error,
    "check_after_error": check_after_error,
    "kv_used_final": engine.allocator.num_used,
    "check_ok": check_ok,
    "faults_injected": obs.counter(
        "faults_injected_total").value(point="llm_chunk_prefill"),
}
json.dump(res, open(out, "w"))
"""


def drill_llm_prefix_cow_leak(tmp):
    """Cancel one of two prefix-sharing streams mid-chunked-prefill
    (llm_chunk_prefill fault): the survivor keeps exact dense parity,
    blocks stay held while referenced, and the pool drains to zero."""
    script = os.path.join(tmp, "llm_prefix_cow_leak.py")
    with open(script, "w") as f:
        f.write(_LLM_PREFIX_COW_LEAK)
    out = os.path.join(tmp, "llm_prefix_cow_leak.json")
    # chunk hits: A prefills in 2 chunks (16 tokens / 8), B's shared
    # prefix leaves 11 tokens = 2 more chunks; at=4 lands in B's
    # SECOND chunk — mid-prefill, after its COW copy fired
    proc = subprocess.run(
        [sys.executable, script, out],
        env=_env(tmp,
                 fault_spec="llm_chunk_prefill:at=4:exc=RuntimeError"),
        capture_output=True, text=True, timeout=300)
    _check(proc.returncode == 0,
           f"cow-leak run died rc={proc.returncode}\n{proc.stderr}")
    res = json.load(open(out))
    _check(res["faults_injected"] == 1,
           f"faults_injected_total{{point=llm_chunk_prefill}} should "
           f"be 1: {res}")
    _check(res["n_error"] == 1 and res["error_seq"] == res["sid_b"],
           f"exactly the prefix-sharing stream B should die "
           f"mid-prefill: {res}")
    _check(any("fault injected" in m for m in res["error_msgs"]),
           f"error event does not carry the injected fault: {res}")
    _check(res["max_shared"] > 0 and res["prefix_hits"] >= 14,
           f"B never actually shared A's prefix blocks: {res}")
    _check(res["cow_copies"] >= 1,
           f"B's divergent write never triggered copy-on-write: {res}")
    _check(res["used_after_error"] and res["check_after_error"],
           f"freeing dead B released blocks still referenced by A "
           f"(or broke allocator invariants): {res}")
    _check(res["a_tokens"] == res["dense_ref"],
           f"survivor diverged from the dense reference after B's "
           f"mid-prefill death: {res}")
    _check(res["kv_used_final"] == 0 and res["check_ok"],
           f"KV blocks leaked after the drill: {res}")
    return ("mid-prefill death of a prefix-sharing stream left the "
            "survivor bit-exact and leaked zero KV blocks")


_LLM_SPEC_ROLLBACK = r"""
import json, sys
import numpy as np
import jax.numpy as jnp
import paddle_tpu as pt
from paddle_tpu import observability as obs
from paddle_tpu.models import GPTLanguageModel
from paddle_tpu.serving_llm import LLMEngine

out = sys.argv[1]
pt.set_flags({"speculative_k": 3})
model = GPTLanguageModel()
# self-drafting: accept rate is exactly 1.0 at temp 0, so every
# surviving sequence must keep token-for-token dense parity even
# though the rollback machinery runs under it
engine = LLMEngine(model, block_size=4, pool_blocks=32,
                   draft_model=model)
prompt_a = list(range(1, 10))    # 9 tokens
prompt_b = list(range(40, 52))   # 12 tokens
# 16 tokens = four k=3 draft windows per sequence: the survivor is
# still mid-decode (holding KV blocks) when the at=3 fault kills the
# other stream in its second window
sid_a = engine.add_request(np.asarray(prompt_a, np.int32),
                           max_new_tokens=16)
sid_b = engine.add_request(np.asarray(prompt_b, np.int32),
                           max_new_tokens=16)
toks, errors = {}, []
used_after_error = check_after_error = accepted_at_error = None
for step in range(64):
    for e in engine.step():
        if e["type"] == "token":
            toks.setdefault(e["seq_id"], []).append(int(e["token"]))
        elif e["type"] == "error":
            errors.append(e)
            accepted_at_error = engine.spec_accepted_total
            used_after_error = engine.allocator.num_used
            try:
                engine.allocator.check()
                check_after_error = True
            except AssertionError:
                check_after_error = False
    if not engine.active():
        break
check_ok = True
try:
    engine.allocator.check()
except AssertionError:
    check_ok = False
surv = sid_b if errors and errors[0]["seq_id"] == sid_a else sid_a
surv_prompt = prompt_b if surv == sid_b else prompt_a
ref = [int(t) for t in np.asarray(model.generate(
    jnp.asarray([surv_prompt], jnp.int32), max_new_tokens=16))[0]]
res = {
    "n_error": len(errors),
    "error_seq": errors[0]["seq_id"] if errors else None,
    "error_msgs": [e["error"] for e in errors],
    "sid_a": sid_a, "sid_b": sid_b,
    "survivor_tokens": toks.get(surv, []),
    "dense_ref": ref,
    "accepted_at_error": accepted_at_error,
    "spec_proposed": engine.spec_proposed_total,
    "spec_accepted": engine.spec_accepted_total,
    "used_after_error": used_after_error,
    "check_after_error": check_after_error,
    "kv_used_final": engine.allocator.num_used,
    "check_ok": check_ok,
    "faults_injected": obs.counter(
        "faults_injected_total").value(point="llm_spec_verify"),
}
json.dump(res, open(out, "w"))
"""


def drill_llm_spec_rollback(tmp):
    """Fault a speculative verify step after at least one accepted
    draft window has been committed (llm_spec_verify fault): the
    failed sequence's KV — including any uncommitted draft window —
    is released, the co-batched survivor keeps exact dense parity,
    and the pool drains to zero with clean allocator invariants."""
    script = os.path.join(tmp, "llm_spec_rollback.py")
    with open(script, "w") as f:
        f.write(_LLM_SPEC_ROLLBACK)
    out = os.path.join(tmp, "llm_spec_rollback.json")
    # hits count per sequence per decode step in admission order, so
    # at=3 always lands in the SECOND speculative step of whichever
    # sequence it strikes — at least one full draft window (k tokens +
    # bonus) is already committed when the fault fires
    proc = subprocess.run(
        [sys.executable, script, out],
        env=_env(tmp,
                 fault_spec="llm_spec_verify:at=3:exc=RuntimeError"),
        capture_output=True, text=True, timeout=300)
    _check(proc.returncode == 0,
           f"spec-rollback run died rc={proc.returncode}\n"
           f"{proc.stderr}")
    res = json.load(open(out))
    _check(res["faults_injected"] == 1,
           f"faults_injected_total{{point=llm_spec_verify}} should "
           f"be 1: {res}")
    _check(res["n_error"] == 1,
           f"exactly one sequence should die mid-verify: {res}")
    _check(any("fault injected" in m for m in res["error_msgs"]),
           f"error event does not carry the injected fault: {res}")
    _check(res["accepted_at_error"] is not None
           and res["accepted_at_error"] >= 3,
           f"no draft window was accepted before the fault — the "
           f"drill never exercised commit-then-rollback: {res}")
    _check(res["used_after_error"] and res["check_after_error"],
           f"failing one speculative stream broke allocator "
           f"invariants or freed the survivor's blocks: {res}")
    _check(res["survivor_tokens"] == res["dense_ref"],
           f"survivor diverged from the dense reference after the "
           f"co-batched stream died mid-verify: {res}")
    _check(res["spec_accepted"] == res["spec_proposed"] > 0,
           f"self-draft accept rate should stay exactly 1.0 for "
           f"windows that reached the verifier: {res}")
    _check(res["kv_used_final"] == 0 and res["check_ok"],
           f"KV blocks leaked after the drill: {res}")
    return ("mid-verify death of a speculative stream rolled its KV "
            "back cleanly; survivor kept exact parity, pool drained "
            "to zero")


_LLM_FLIGHT_DECK = r"""
import json, sys
import numpy as np
import paddle_tpu as pt
from paddle_tpu import observability as obs
from paddle_tpu.observability import seqtrace
from paddle_tpu.models import GPTConfig, GPTLanguageModel
from paddle_tpu.serving_llm import LLMEngine
from tools import serving_report

out = sys.argv[1]
# speculation starts OFF so every decoder grows exactly one token per
# step — the pool-pressure preemption lands deterministically while
# the victim is still prefilling
pt.set_flags({"kv_prefix_sharing": True, "prefill_chunk_tokens": 4,
              "speculative_k": 0})
model = GPTLanguageModel()
# a 1-layer draft disagrees with the target often enough that some
# verify windows MUST roll back (the flight-deck event under test)
draft = GPTLanguageModel(GPTConfig(num_layers=1))
engine = LLMEngine(model, block_size=4, pool_blocks=16,
                   draft_model=draft)
shared = list(range(1, 11))               # 10 tokens: 2.5 blocks
prompt_a = shared + [20, 21]              # 12 tokens: the prefix OWNER
prompt_b = list(range(100, 108))          # 8 tokens: pool ballast
prompt_v = shared + list(range(30, 60))   # 40 tokens, diverges at 10
# the cast: A owns the shared prefix and must outlive the victim's
# readmission (its partial tail block is what the victim re-COWs); B
# is ballast whose block growth exhausts the pool mid-way through the
# victim's 8-chunk prefill, preempting the YOUNGEST — the victim.
# When the victim readmits, its own freed blocks are the slack that
# lets make_private take a real copy instead of degenerating into a
# preempt-the-sharer retry (which copies nothing).
sid_a = engine.add_request(np.asarray(prompt_a, np.int32),
                           max_new_tokens=12)
sid_b = engine.add_request(np.asarray(prompt_b, np.int32),
                           max_new_tokens=10)
sid_v = None
toks = {}
spec_on = False
for step in range(200):
    if step == 4:
        # A and B are decoding: V admits sharing A's 10-token prefix
        # (COW material in A's partial block 2)
        sid_v = engine.add_request(np.asarray(prompt_v, np.int32),
                                   max_new_tokens=8)
    for e in engine.step():
        if e["type"] == "token":
            toks.setdefault(e["seq_id"], []).append(int(e["token"]))
    engine._audit()
    if not spec_on and engine.allocator.cow_copies_total >= 2:
        # the post-readmit COW landed: turn speculation on so the
        # victim's decode proposes draft windows (and rolls some back
        # — the draft is 1-layer, the target is not)
        pt.set_flags({"speculative_k": 3})
        spec_on = True
    if not engine.active():
        break
check_ok = True
try:
    engine.allocator.check()
except AssertionError:
    check_ok = False
tl = seqtrace.ring().get(sid_v)
timelines, steps = serving_report.load_rings()
rep = serving_report.analyze(timelines, steps, threshold_ms=1.0)
res = {
    "sid_a": sid_a, "sid_v": sid_v,
    "outcome": tl["outcome"] if tl else None,
    "events": tl["events"] if tl else [],
    "v_tokens": len(toks.get(sid_v, [])),
    "preemptions": engine.scheduler.preemptions_total,
    "cow_copies": engine.allocator.cow_copies_total,
    "spec_proposed": engine.spec_proposed_total,
    "spec_accepted": engine.spec_accepted_total,
    "kv_used_final": engine.allocator.num_used,
    "check_ok": check_ok,
    "steps_recorded": len(steps),
    "findings_v": [f for f in rep["findings"]
                   if f["seq_id"] == sid_v],
}
json.dump(res, open(out, "w"))
"""


def drill_llm_flight_deck(tmp):
    """Flight-deck lifecycle drill: a prefix-sharing stream is
    preempted MID-prefill by an older stream's speculative growth,
    re-prefills with a fresh copy-on-write at the divergence point,
    and takes draft-window rollbacks — its /llm/seqs timeline must
    order preempted < cow_copy < spec_window{rollback} by monotonic
    stamp, serving_report must attribute its gaps to exactly those
    causes with exclusive buckets, and ptlint (clock-hygiene among the
    passes) must stay green on the flight-deck sources."""
    script = os.path.join(tmp, "llm_flight_deck.py")
    with open(script, "w") as f:
        f.write(_LLM_FLIGHT_DECK)
    out = os.path.join(tmp, "llm_flight_deck.json")
    proc = subprocess.run(
        [sys.executable, script, out],
        env=_env(tmp), capture_output=True, text=True, timeout=300)
    _check(proc.returncode == 0,
           f"flight-deck run died rc={proc.returncode}\n{proc.stderr}")
    res = json.load(open(out))
    _check(res["outcome"] == "finished" and res["v_tokens"] == 8,
           f"victim stream should finish all 8 tokens: {res}")
    evs = res["events"]
    stamps = [e["t_mono"] for e in evs]
    _check(stamps == sorted(stamps),
           "timeline stamps are not monotonically non-decreasing")
    names = [e["ev"] for e in evs]
    _check(names[0] == "queued" and names[-1] == "finished",
           f"timeline must run queued..finished: {names}")
    pre = [i for i, e in enumerate(evs) if e["ev"] == "preempted"]
    _check(bool(pre) and res["preemptions"] >= 1,
           f"victim was never preempted: {names}")
    _check(any(e["ev"] == "prefill_chunk" for e in evs[:pre[0]])
           and evs[pre[0]].get("tokens") == 0,
           f"preemption did not land MID-prefill (chunks before it, "
           f"no tokens yet): {names}")
    readmit = [i for i, e in enumerate(evs) if e["ev"] == "readmitted"]
    _check(bool(readmit) and readmit[0] > pre[0],
           f"no readmission after the preemption: {names}")
    cow = [i for i, e in enumerate(evs)
           if e["ev"] == "cow_copy" and i > readmit[0]]
    _check(bool(cow) and res["cow_copies"] >= 2,
           f"recompute prefill never re-fired copy-on-write at the "
           f"divergence point: {names}")
    roll = [i for i, e in enumerate(evs)
            if e["ev"] == "spec_window" and e.get("rollback")]
    _check(bool(roll) and roll[-1] > cow[0],
           f"no draft-window rollback after the post-readmit COW: "
           f"{names}")
    _check(res["spec_proposed"] > res["spec_accepted"],
           f"draft never disagreed with the target — rollback path "
           f"unexercised: {res['spec_proposed']} proposed, "
           f"{res['spec_accepted']} accepted")
    # attribution: the engineered causes must carry real ledger weight
    vf = res["findings_v"]
    _check(bool(vf), "serving_report found no gaps for the victim")
    for f in vf:
        total = sum(f["buckets"].values())
        _check(abs(total - f["gap_ms"]) <= max(0.05 * f["gap_ms"], 0.5),
               f"buckets not exclusive/complete: {f}")
    first = [f for f in vf if f["first_token"]]
    _check(bool(first) and first[0]["cause"] == "preempt_recompute",
           f"victim TTFT gap should be attributed to "
           f"preempt_recompute: {first}")
    _check(any(f["buckets"]["cow_copy"] > 0 for f in vf)
           and any(f["buckets"]["spec_rollback"] > 0 for f in vf),
           f"cow_copy / spec_rollback never charged: {vf}")
    _check(res["steps_recorded"] > 0 and res["kv_used_final"] == 0
           and res["check_ok"],
           f"step ring empty or KV leaked after the drill: {res}")
    # the attribution above only holds if every stamp it subtracted
    # came from the monotonic clock — keep the linter's word for it
    lint = subprocess.run(
        [sys.executable, os.path.join(ROOT, "tools", "ptlint.py"),
         os.path.join(ROOT, "paddle_tpu", "observability"),
         os.path.join(ROOT, "paddle_tpu", "serving_llm")],
        capture_output=True, text=True, timeout=120)
    _check(lint.returncode == 0,
           f"ptlint (clock-hygiene et al) not green on the flight "
           f"deck:\n{lint.stdout}\n{lint.stderr}")
    return ("mid-prefill preemption, re-COW and spec rollback all "
            "landed on one timeline in stamp order; gaps attributed "
            "to the engineered causes; ptlint green")


def drill_exact_resume(tmp):
    """SIGKILL mid-epoch + v3 resume == uninterrupted run, bitwise."""
    try:
        from tools import replay_check
    except ImportError:  # run from inside tools/
        import replay_check
    try:
        return replay_check.run_check(tmp)
    except replay_check.CheckFailure as e:
        raise DrillFailure(str(e)) from e


def _driver_metrics_on():
    """Enable driver-process metrics for the router drills (their
    router runs in the driver so its counters are asserted directly);
    returns the previous value for restoration."""
    import paddle_tpu as pt
    from paddle_tpu.flags import GLOBAL_FLAGS
    prev = bool(GLOBAL_FLAGS.get("enable_metrics"))
    pt.set_flags({"enable_metrics": True, "metrics_port": -1})
    return prev


def drill_router_backend_kill(tmp):
    """SIGKILL one of two backends mid-stream (after >= 2 delivered
    tokens): the front-door router resumes on the survivor and the
    client-visible token sequence is BITWISE identical to an
    uninterrupted single-backend run — at temperature 0 AND 0.8 —
    with exactly one failover counted, zero retries/sheds, and a
    clean KV audit on the SIGTERMed survivor."""
    import numpy as np
    import paddle_tpu as pt
    from paddle_tpu import observability as obs
    from paddle_tpu.inference import Client
    from paddle_tpu.serving_llm.router import Router
    try:
        from tools import llm_router
    except ImportError:  # run from inside tools/
        import llm_router

    prev_metrics = _driver_metrics_on()
    pt.set_flags({"router_retry_backoff_s": 0.0,
                  "router_probe_interval_s": 0.3})
    summaries = []
    try:
        for temp in (0.0, 0.8):
            sub = os.path.join(tmp, f"router_kill_t{int(temp * 10)}")
            os.makedirs(sub, exist_ok=True)
            pa, pfa, audit_a = llm_router._spawn_backend(sub, 0)
            pb, pfb, audit_b = llm_router._spawn_backend(sub, 1)
            router = None
            try:
                port_a = llm_router._wait_port(pa, pfa)
                port_b = llm_router._wait_port(pb, pfb)
                prompt = (np.arange(6, dtype=np.int32) * 5) % 60
                kw = dict(max_new_tokens=20, temperature=temp, seed=11)
                # uninterrupted single-backend reference
                with Client(port=port_a, timeout_s=120.0,
                            deadline_s=120.0) as cli:
                    ref = cli.generate(prompt, **kw).tolist()
                _check(len(ref) == 20, f"reference stunted: {ref}")
                before = obs.counter("router_failovers_total",
                                     "x").value()
                router = Router([("127.0.0.1", port_a),
                                 ("127.0.0.1", port_b)],
                                probe_interval_s=0.3).start()
                got, victim = [], None
                with Client(port=router.port, timeout_s=120.0,
                            deadline_s=120.0) as cli:
                    for i, ch in enumerate(
                            cli.generate_stream(prompt, **kw)):
                        got.extend(int(t)
                                   for t in np.asarray(ch).ravel())
                        if i == 1:
                            snap = router.snapshot()
                            busy = [b["name"]
                                    for b in snap["backends"]
                                    if b["streams_active"] > 0]
                            _check(len(busy) == 1,
                                   f"one backend should hold the "
                                   f"stream: {snap}")
                            vport = int(busy[0].rsplit(":", 1)[1])
                            victim = pa if vport == port_a else pb
                            victim.send_signal(signal.SIGKILL)
                _check(got == ref,
                       f"temp {temp}: spliced stream diverged:\n"
                       f"  got {got}\n  ref {ref}")
                snap = router.snapshot()
                _check(snap["failovers_total"] == 1
                       and snap["retries_total"] == 0
                       and snap["shed_total"] == 0,
                       f"engineered scenario is exactly 1 failover, "
                       f"0 retries, 0 sheds: {snap}")
                _check(obs.counter("router_failovers_total",
                                   "x").value() - before == 1,
                       "router_failovers_total must move by exactly 1")
                victim.wait(10)
                survivor, s_audit = (pb, audit_b) if victim is pa \
                    else (pa, audit_a)
                survivor.send_signal(signal.SIGTERM)
                rc = survivor.wait(60)
                _check(rc == -signal.SIGTERM,
                       f"survivor exit status {rc}")
                audit = json.load(open(s_audit))
                _check(audit["kv_used"] == 0 and audit["check_ok"]
                       and audit["gauges_ok"]
                       and audit["open_streams"] == 0,
                       f"survivor KV audit dirty: {audit}")
                summaries.append(f"temp {temp}: 20 tokens spliced "
                                 f"bitwise")
            finally:
                if router is not None:
                    router.stop()
                for p in (pa, pb):
                    if p.poll() is None:
                        p.kill()
                for p in (pa, pb):
                    try:
                        p.wait(10)
                    except subprocess.TimeoutExpired:
                        pass
    finally:
        pt.set_flags({"enable_metrics": prev_metrics})
    return ("; ".join(summaries) + "; 1 failover each, survivor "
            "audits clean")


_ROUTER_TIGHT_BACKEND = r"""
import sys
import paddle_tpu as pt
from paddle_tpu.inference import Server
from paddle_tpu.models import GPTLanguageModel
from paddle_tpu.serving_llm import LLMEngine

portfile = sys.argv[1]
pt.seed(0)
model = GPTLanguageModel()
# 8-block pool + the 0.5 admission watermark from the env: budget 4
# blocks, each stream projects 3 (4 prompt + 8 new = 12 tokens), so
# each backend admits exactly ONE stream at a time
engine = LLMEngine(model, block_size=4, pool_blocks=8)
srv = Server(None, llm_engine=engine)
with open(portfile, "w") as f:
    f.write(str(srv.port))
srv.serve_forever()
"""


def drill_router_all_saturated(tmp):
    """Flood the router at 4x fleet capacity: the two fully-loaded
    backends refuse extras at admission, and the router sheds those
    streams AT THE DOOR with the aggregated max retry_after_ms hint —
    no router-side queueing, no retries, no breaker trips (saturation
    is not failure), pool back to idle after the flood."""
    import threading
    import numpy as np
    import paddle_tpu as pt
    from paddle_tpu.inference import Client
    from paddle_tpu.serving_llm.router import Router

    prev_metrics = _driver_metrics_on()
    pt.set_flags({"router_retry_backoff_s": 0.0,
                  "router_probe_interval_s": 0.5})
    procs, router = [], None
    try:
        ports = []
        for idx in range(2):
            script = os.path.join(tmp, f"tight_backend_{idx}.py")
            with open(script, "w") as f:
                f.write(_ROUTER_TIGHT_BACKEND)
            portfile = os.path.join(tmp, f"tight_port_{idx}.txt")
            if os.path.exists(portfile):
                os.remove(portfile)
            env = _env(tmp)
            env["FLAGS_kv_admission_watermark"] = "0.5"
            procs.append(subprocess.Popen(
                [sys.executable, script, portfile], env=env,
                stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                text=True))
            ports.append((procs[-1], portfile))
        bound = []
        for proc, portfile in ports:
            deadline = time.time() + 180
            while not os.path.exists(portfile) \
                    and time.time() < deadline:
                if proc.poll() is not None:
                    raise DrillFailure(
                        f"tight backend died during startup\n"
                        f"{proc.communicate()[1]}")
                time.sleep(0.1)
            _check(os.path.exists(portfile),
                   "tight backend never bound")
            bound.append(int(open(portfile).read()))

        router = Router([("127.0.0.1", p) for p in bound],
                        probe_interval_s=0.5).start()
        outcomes, lock = [], threading.Lock()

        def worker(i):
            prompt = np.asarray([1 + i, 2, 3, 4], np.int32)
            cli = Client(port=router.port, timeout_s=120.0,
                         deadline_s=120.0)
            try:
                toks = []
                for ch in cli.generate_stream(prompt,
                                              max_new_tokens=8):
                    toks.extend(int(t) for t in np.asarray(ch).ravel())
                out = ("ok", len(toks))
            except RuntimeError as e:
                out = ("shed", str(e)) \
                    if "all backends saturated" in str(e) \
                    else ("error", str(e))
            except Exception as e:  # noqa: BLE001 — report, not crash
                out = (type(e).__name__, str(e))
            finally:
                cli.close()
            with lock:
                outcomes.append(out)

        threads = [threading.Thread(target=worker, args=(i,))
                   for i in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=300)
        n_ok = sum(1 for o in outcomes if o[0] == "ok" and o[1] == 8)
        sheds = [o[1] for o in outcomes if o[0] == "shed"]
        _check(n_ok + len(sheds) == 8,
               f"flood must split into completed + door-shed, got "
               f"{outcomes}")
        _check(n_ok >= 2, f"capacity-2 fleet should finish at least "
               f"2 streams: {outcomes}")
        _check(len(sheds) >= 4,
               f"a 4x-capacity flood should shed most of the wave: "
               f"{outcomes}")
        _check(all("retry_after_ms=" in s for s in sheds),
               f"every shed must carry the aggregated retry-after "
               f"hint: {sheds}")
        snap = router.snapshot()
        _check(snap["shed_total"] == len(sheds),
               f"router_shed_total disagrees with client sheds: "
               f"{snap} vs {len(sheds)}")
        _check(snap["failovers_total"] == 0
               and snap["retries_total"] == 0,
               f"saturation must not look like failure (no retries, "
               f"no failovers): {snap}")
        # the stream thread decrements its gauge just after the
        # terminal frame the client saw — allow that cleanup a moment
        deadline = time.time() + 10
        while snap["streams_active"] != 0 and time.time() < deadline:
            time.sleep(0.05)
            snap = router.snapshot()
        _check(snap["streams_active"] == 0,
               f"router must hold no queued streams after the flood: "
               f"{snap}")
        _check(all(b["breaker"]["state"] == "closed"
                   and b["breaker"]["opened_total"] == 0
                   for b in snap["backends"]),
               f"admission rejections must never trip a breaker: "
               f"{snap}")
    finally:
        if router is not None:
            router.stop()
        for p in procs:
            if p.poll() is None:
                p.kill()
        for p in procs:
            try:
                p.wait(10)
            except subprocess.TimeoutExpired:
                pass
        pt.set_flags({"enable_metrics": prev_metrics})
    return (f"{n_ok} streams finished, {len(sheds)} door-shed with "
            f"retry hints; 0 retries, 0 failovers, breakers closed")


DRILLS = {
    "kill_mid_save": drill_kill_mid_save,
    "corrupt_leaf": drill_corrupt_leaf,
    "sigterm_mid_fit": drill_sigterm_mid_fit,
    "crash_loop": drill_crash_loop,
    "nonfinite_skip": drill_nonfinite_skip,
    "exact_resume": drill_exact_resume,
    "stream_disconnect": drill_stream_disconnect,
    "llm_overload_shed": drill_llm_overload_shed,
    "llm_tenant_flood": drill_llm_tenant_flood,
    "slo_burn_alert": drill_slo_burn_alert,
    "hang_doctor": drill_hang_doctor,
    "llm_drain_sigterm": drill_llm_drain_sigterm,
    "llm_decode_error": drill_llm_decode_error,
    "llm_prefix_cow_leak": drill_llm_prefix_cow_leak,
    "llm_spec_rollback": drill_llm_spec_rollback,
    "llm_flight_deck": drill_llm_flight_deck,
    "router_backend_kill": drill_router_backend_kill,
    "router_all_saturated": drill_router_all_saturated,
}


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--self-test", action="store_true",
                        help="run every drill on CPU and report")
    parser.add_argument("--drill", choices=sorted(DRILLS),
                        help="run one drill")
    parser.add_argument("--keep", action="store_true",
                        help="keep the scratch directory")
    parser.add_argument("--list", action="store_true",
                        help="print the drill inventory and exit")
    args = parser.parse_args(argv)
    if args.list:
        # inventory only: exits before the jax import below, so it is
        # cheap enough for CI to sanity-check the drill roster
        for name in sorted(DRILLS):
            doc = (DRILLS[name].__doc__ or "").strip()
            first = " ".join(
                line.strip() for line in doc.splitlines()[:3]).strip()
            print(f"{name:20s} {first}")
        return 0
    if not args.self_test and not args.drill:
        parser.error("pass --self-test, --drill NAME, or --list")

    # the driver half imports paddle_tpu itself — force CPU first
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax
    jax.config.update("jax_platforms", "cpu")

    names = [args.drill] if args.drill else sorted(DRILLS)
    tmp = tempfile.mkdtemp(prefix="chaos_drill_")
    failures = 0
    try:
        for name in names:
            t0 = time.time()
            try:
                summary = DRILLS[name](tmp)
                print(f"[chaos] {name}: OK ({time.time() - t0:.1f}s) — "
                      f"{summary}")
            except DrillFailure as e:
                failures += 1
                print(f"[chaos] {name}: FAIL — {e}", file=sys.stderr)
    finally:
        if args.keep:
            print(f"[chaos] scratch kept at {tmp}")
        else:
            shutil.rmtree(tmp, ignore_errors=True)
    if failures:
        print(f"chaos drill: {failures} of {len(names)} drills FAILED",
              file=sys.stderr)
        return 1
    print(f"chaos drill self-test OK ({len(names)} drills)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
