"""Prove the persistent compile cache: warm processes skip cold XLA.

Usage:
    python tools/compile_cache_report.py [TRACE_DIR | metrics.json]
                                        [--self-test]

Renders the compile-side view of an exported ``metrics.json``: the
goodput ledger's ``jit_compile_cold`` vs ``jit_compile_cache_hit``
seconds and the ``compile_cache_{hits,misses}_total`` counters fed by
jax's persistent compilation cache (FLAGS_compile_cache_dir).

``--self-test`` is the no-TPU CI drill behind ISSUE 8's acceptance
criterion: it runs the SAME tiny fit in two sequential subprocesses
sharing one fresh cache directory and asserts the second (warm)
process books < 10% of the first process's cold-compile seconds while
its cache-hit counter is > 0 — i.e. a restarted job really does load
its executables from disk instead of paying the cold compiles again
(PR 5's skip-step guard changed every train step's HLO, so before this
cache every fresh process paid them in full).
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)


def _counter_total(metrics: dict, name: str) -> float:
    return sum(s.get("value", 0)
               for s in metrics.get(name, {}).get("series", []))


def render(snap: dict) -> str:
    goodput = snap.get("goodput") or {}
    buckets = goodput.get("buckets", {})
    metrics = snap.get("metrics", {})
    cold = buckets.get("jit_compile_cold", 0.0)
    warm = buckets.get("jit_compile_cache_hit", 0.0)
    hits = _counter_total(metrics, "compile_cache_hits_total")
    misses = _counter_total(metrics, "compile_cache_misses_total")
    lines = ["== compile cache ==",
             f"{'jit_compile_cold':<24} {cold:>10.3f} s",
             f"{'jit_compile_cache_hit':<24} {warm:>10.3f} s",
             f"{'cache hits':<24} {int(hits):>10}",
             f"{'cache misses':<24} {int(misses):>10}"]
    if not buckets:
        lines.append("(no goodput section — run the fit with "
                     "FLAGS_enable_metrics=1)")
    elif cold + warm > 0:
        lines.append(f"{'warm share':<24} "
                     f"{100 * warm / (cold + warm):>9.1f} %")
    return "\n".join(lines)


def report(path: str) -> int:
    mpath = path
    if os.path.isdir(path):
        mpath = os.path.join(path, "metrics.json")
    if not os.path.exists(mpath):
        print(f"no metrics.json at {mpath} — run with "
              "FLAGS_enable_metrics=1 and FLAGS_trace_dir set",
              file=sys.stderr)
        return 1
    with open(mpath) as f:
        snap = json.load(f)
    print(render(snap))
    return 0


# ------------------------------------------------------------------ CI

def _child(trace_dir: str, cache_dir: str) -> int:
    """One fresh-interpreter fit against a shared persistent cache —
    the unit the self-test measures twice."""
    import numpy as np

    import paddle_tpu as pt

    pt.set_flags({"enable_metrics": True, "trace_dir": trace_dir,
                  "compile_cache_dir": cache_dir})

    class MLP(pt.nn.Layer):
        def __init__(self):
            super().__init__()
            self.fc1 = pt.nn.Linear(8, 16)
            self.fc2 = pt.nn.Linear(16, 4)

        def forward(self, x):
            return self.fc2(pt.nn.functional.relu(self.fc1(x)))

    rng = np.random.default_rng(0)
    # compile seconds, not step count, carry the cold/warm contrast —
    # keep the fit tiny so the drill stays cheap inside tier-1
    n = 8 * 4
    x = rng.normal(size=(n, 8)).astype(np.float32)
    y = rng.integers(0, 4, n).astype(np.int64)
    loader = pt.data.DataLoader(pt.data.TensorDataset(x, y),
                                batch_size=4)
    m = pt.hapi.Model(MLP())
    m.prepare(optimizer=pt.optimizer.Adam(learning_rate=1e-3),
              loss=pt.nn.CrossEntropyLoss())
    m.fit(loader, epochs=1, verbose=0)
    from paddle_tpu import observability as obs
    obs.export_all(trace_dir)
    return 0


def _run_child(trace_dir: str, cache_dir: str) -> dict:
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    # a stray dev-cache env var would defeat the drill's fresh-dir
    # cold/warm contrast
    env.pop("JAX_COMPILATION_CACHE_DIR", None)
    proc = subprocess.run(
        [sys.executable, os.path.abspath(__file__), "--child",
         trace_dir, cache_dir],
        capture_output=True, text=True, env=env, timeout=480)
    assert proc.returncode == 0, (proc.stdout, proc.stderr)
    with open(os.path.join(trace_dir, "metrics.json")) as f:
        return json.load(f)


def self_test() -> int:
    import tempfile

    with tempfile.TemporaryDirectory() as d:
        cache = os.path.join(d, "cache")
        snap1 = _run_child(os.path.join(d, "run1"), cache)
        snap2 = _run_child(os.path.join(d, "run2"), cache)
        b1 = snap1["goodput"]["buckets"]
        b2 = snap2["goodput"]["buckets"]
        cold1 = b1.get("jit_compile_cold", 0.0)
        cold2 = b2.get("jit_compile_cold", 0.0)
        hits2 = _counter_total(snap2.get("metrics", {}),
                               "compile_cache_hits_total")
        misses1 = _counter_total(snap1.get("metrics", {}),
                                 "compile_cache_misses_total")
        print("== cold process ==")
        print(render(snap1))
        print("\n== warm process ==")
        print(render(snap2))
        # process 1 populated a fresh cache: real cold compiles, all
        # misses on lookup
        assert cold1 > 0, b1
        assert misses1 > 0, snap1["metrics"].keys()
        # process 2 is warm: executables load from the shared dir —
        # near-zero cold seconds (< 10% of process 1's), hits counted
        assert hits2 > 0, snap2["metrics"].keys()
        assert cold2 < 0.10 * cold1, (cold1, cold2)
    print("\nself-test OK")
    return 0


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("path", nargs="?", default="")
    ap.add_argument("--self-test", action="store_true")
    ap.add_argument("--child", nargs=2,
                    metavar=("TRACE_DIR", "CACHE_DIR"),
                    default=None,
                    help=argparse.SUPPRESS)  # internal: one measured fit
    args = ap.parse_args()
    if args.child:
        return _child(*args.child)
    if args.self_test:
        return self_test()
    path = args.path
    if not path:
        from paddle_tpu.flags import GLOBAL_FLAGS
        path = GLOBAL_FLAGS.get("trace_dir") or "/tmp/pt_trace"
    return report(path)


if __name__ == "__main__":
    sys.exit(main())
