"""CI check: every framework flag must have help text and docs.

Thin shim over the ``flags-doc`` ptlint pass
(``paddle_tpu/analysis/flags_doc.py``) — the AST walk, the doc scan,
and the CLI output live there now; this file only preserves the
historical entry point and public API (``collect_flags`` /
``docs_text`` / ``main``).  Run ``python tools/ptlint.py --all`` for
the full pass registry, or this script for just the flags contract.

Usage: python tools/check_flags_doc.py   (exit 0 ok, 1 violations)
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from ptlint import ANALYSIS  # noqa: E402

_impl = ANALYSIS.flags_doc

ROOT = _impl.ROOT
FLAGS_PY = _impl.FLAGS_PY
DOCS_DIR = _impl.DOCS_DIR

collect_flags = _impl.collect_flags
docs_text = _impl.docs_text
main = _impl.cli_main


if __name__ == "__main__":
    sys.exit(main())
