"""CI check: every framework flag must have help text and docs.

Walks the ``define_flag`` calls in ``paddle_tpu/flags.py`` by AST (no
framework import, so the check runs in milliseconds with no jax) and
fails when

- a flag's ``help`` argument is empty or missing, or
- the flag is not mentioned (as ``FLAGS_<name>``) anywhere under
  ``docs/``.

``docs/flags.md`` is the canonical index; adding a new flag means
adding its row there (or documenting it in a feature doc). This is the
observability analogue of the reference's convention that every
``DEFINE_*`` in platform/flags.cc carries a descriptive string.

Usage: python tools/check_flags_doc.py   (exit 0 ok, 1 violations)
"""

from __future__ import annotations

import ast
import os
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FLAGS_PY = os.path.join(ROOT, "paddle_tpu", "flags.py")
DOCS_DIR = os.path.join(ROOT, "docs")


def collect_flags(path: str = FLAGS_PY):
    """[(name, has_help)] for every define_flag(...) call."""
    tree = ast.parse(open(path).read(), filename=path)
    out = []
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Name)
                and node.func.id == "define_flag"):
            continue
        if not node.args or not isinstance(node.args[0], ast.Constant):
            continue
        name = node.args[0].value
        help_node = None
        if len(node.args) >= 3:
            help_node = node.args[2]
        for kw in node.keywords:
            if kw.arg == "help":
                help_node = kw.value
        has_help = (isinstance(help_node, ast.Constant)
                    and isinstance(help_node.value, str)
                    and bool(help_node.value.strip()))
        out.append((name, has_help))
    return out


def docs_text(docs_dir: str = DOCS_DIR) -> str:
    chunks = []
    for dirpath, _, files in os.walk(docs_dir):
        for f in files:
            if f.endswith((".md", ".rst", ".txt")):
                with open(os.path.join(dirpath, f)) as fh:
                    chunks.append(fh.read())
    return "\n".join(chunks)


def main() -> int:
    flags = collect_flags()
    if not flags:
        print("check_flags_doc: no define_flag calls found "
              f"in {FLAGS_PY} — parser broken?", file=sys.stderr)
        return 1
    docs = docs_text()
    bad_help = [n for n, has_help in flags if not has_help]
    undocumented = [n for n, _ in flags if f"FLAGS_{n}" not in docs]
    for n in bad_help:
        print(f"FLAGS_{n}: empty or missing help= in flags.py",
              file=sys.stderr)
    for n in undocumented:
        print(f"FLAGS_{n}: not documented anywhere under docs/ "
              "(add it to docs/flags.md)", file=sys.stderr)
    if bad_help or undocumented:
        print(f"check_flags_doc: {len(bad_help)} empty-help, "
              f"{len(undocumented)} undocumented "
              f"(of {len(flags)} flags)", file=sys.stderr)
        return 1
    print(f"check_flags_doc: OK ({len(flags)} flags documented)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
