"""SLO & alert table: render the judgment layer for one process/fleet.

Usage:
    python tools/slo_report.py --url http://host:port   # live exporter
    python tools/slo_report.py --input alerts.json      # endpoint dump
    python tools/slo_report.py --json                   # machine output
    python tools/slo_report.py --self-test              # no-TPU CI drill

Reads the exporter's ``/alerts`` + ``/slo`` endpoints
(observability/slo.py over observability/tsdb.py) and prints one row
per SLO: alert state, exact error-budget remaining, the observed burn
rate for each window pair (fast 5m/1h @ 14.4, slow 30m/6h @ 6 —
scaled by ``FLAGS_slo_window_scale``), and lifetime compliance.

``--self-test`` is the no-TPU CI hook: it boots a real CPU serving
stack (LLMEngine + inference.Server + threaded Clients), then drives
an **engineered overload** — an ``llm_prefill:sleep=`` fault (TTFT
blows past the 1 s objective) plus a client flood into a 0.5 KV
admission watermark (availability burns on rejections) — and asserts
the full alert lifecycle: the fast-burn availability and TTFT-p99
alerts trip while the overload runs, every transition lands in the
crash flight recorder, both alerts resolve after the load stops, the
error-budget arithmetic matches hand-computed counter math exactly,
and a 200-stream flood leaves the tsdb sample rings and the alert
transition rings provably bounded with zero KV leak and a clean
engine audit.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Any, Dict, List, Optional

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if ROOT not in sys.path:
    sys.path.insert(0, ROOT)


# ------------------------------------------------------------------ load

def load_url(url: str) -> Dict[str, Any]:
    import urllib.request

    def fetch(path):
        with urllib.request.urlopen(url.rstrip("/") + path,
                                    timeout=10) as r:
            return json.loads(r.read().decode())

    return {"alerts": fetch("/alerts"), "slo": fetch("/slo")}


def load_file(path: str) -> Dict[str, Any]:
    with open(path) as f:
        blob = json.load(f)
    if "alerts" in blob and "slo" in blob:
        return blob
    # a bare /alerts dump still renders (no compliance column)
    return {"alerts": blob, "slo": {"slos": []}}


def load_local() -> Dict[str, Any]:
    """In-process view (after driving an engine in this interpreter)."""
    from paddle_tpu.observability import slo as _slo
    eng = _slo.engine()
    return {"alerts": eng.alerts_view(), "slo": eng.slo_view()}


# ---------------------------------------------------------------- render

def _fmt_burn(w: Dict[str, Any]) -> str:
    s, l = w["short"]["burn_rate"], w["long"]["burn_rate"]
    flag = "*" if w.get("over") else ""
    return f"{s:.1f}/{l:.1f}{flag}"


def render(view: Dict[str, Any]) -> int:
    alerts = view.get("alerts") or {}
    slo_view = view.get("slo") or {}
    compliance = {s["spec"]["name"]: s
                  for s in slo_view.get("slos") or []}
    rows: List[tuple] = []
    for a in alerts.get("alerts") or []:
        name = a["slo"]
        comp = compliance.get(name) or {}
        life = comp.get("lifetime") or {}
        spec = comp.get("spec") or {}
        windows = a.get("windows") or {}
        rows.append((
            name,
            a.get("state", "?"),
            f"{a.get('budget_remaining', float('nan')):+.4f}",
            _fmt_burn(windows["fast"]) if "fast" in windows else "-",
            _fmt_burn(windows["slow"]) if "slow" in windows else "-",
            (f"{life['compliance']:.4%}" if life.get("total") else "-"),
            (f"{spec['target']:.3f}" if spec.get("target") else "-"),
        ))
    worst = alerts.get("worst_state", "inactive")
    print(f"SLO engine: {len(rows)} objective(s), "
          f"worst state = {worst}")
    cols = ("slo", "state", "budget", "fast s/l*", "slow s/l*",
            "compliance", "target")
    widths = [max(len(c), *(len(str(r[i])) for r in rows)) if rows
              else len(c) for i, c in enumerate(cols)]
    print("  ".join(c.ljust(w) for c, w in zip(cols, widths)))
    for r in rows:
        print("  ".join(str(v).ljust(w) for v, w in zip(r, widths)))
    firing = [r[0] for r in rows if r[1] == "firing"]
    if firing:
        print(f"FIRING: {', '.join(firing)}", file=sys.stderr)
    return 1 if firing else 0


# ------------------------------------------------------------- self-test

# fast pair becomes 3s/36s, slow pair 18s/216s: the whole alert
# lifecycle (trip under load, resolve after) runs in CI seconds with
# the production burn thresholds (14.4 / 6) untouched
_SCALE = 0.01
_TICK_S = 0.1


def _counter_sum(name: str) -> float:
    """Lifetime value of a counter summed across label sets, 0.0 when
    it never registered — the same basis SLOSpec.lifetime_counts uses."""
    from paddle_tpu.observability import metrics as m
    inst = m.registry().get(name)
    if inst is None:
        return 0.0
    return float(sum(s["value"] for s in inst._snapshot()))


def _drive_clients(port: int, n: int, max_new: int = 4):
    """One flood wave: n threaded clients, one generate() each.
    Returns (n_ok, n_rejected)."""
    import threading

    import numpy as np

    from paddle_tpu.inference import Client

    results: List[str] = []
    lock = threading.Lock()
    prompt = np.asarray([5, 6, 7, 8, 9], np.int32)

    def worker():
        cli = Client(port=port, timeout_s=120.0)
        try:
            cli.generate(prompt, max_new_tokens=max_new, retry=False)
            with lock:
                results.append("ok")
        except RuntimeError:  # admission rejected (terminal -1 frame)
            with lock:
                results.append("rejected")
        finally:
            cli.close()

    threads = [threading.Thread(target=worker) for _ in range(n)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    return results.count("ok"), results.count("rejected")


def self_test() -> int:
    import time

    import numpy as np

    import paddle_tpu as pt
    from paddle_tpu.inference import Server
    from paddle_tpu.models import GPTLanguageModel
    from paddle_tpu.observability import flight
    from paddle_tpu.observability import metrics as m
    from paddle_tpu.observability import slo as slo_mod
    from paddle_tpu.observability import tsdb as tsdb_mod
    from paddle_tpu.serving_llm import LLMEngine
    from paddle_tpu.sysconfig import enable_compile_cache

    enable_compile_cache()
    pt.set_flags({"enable_metrics": True, "metrics_port": -1,
                  "slo_window_scale": _SCALE,
                  "tsdb_interval_s": _TICK_S,
                  "fault_spec": "", "kv_admission_watermark": 0.0})
    slo_mod.ensure_default_pack()
    eng = slo_mod.engine()
    ring = tsdb_mod.ring()

    def states() -> Dict[str, str]:
        return {a["slo"]: a["state"] for a in eng.evaluate()}

    model = GPTLanguageModel()
    # 8-block pool + 0.5 watermark (armed below) = admission budget of
    # 4 blocks; each request projects 3, so a flood MUST see rejections
    engine = LLMEngine(model, block_size=4, pool_blocks=8)
    srv = Server(None, llm_engine=engine)
    try:
        # -- warm-up BEFORE the first tsdb sample: the jit-compile
        # TTFT (seconds on CPU) lands inside the baseline sample and
        # is invisible to every windowed increase
        _drive_clients(srv.port, 1)
        _drive_clients(srv.port, 1)
        tsdb_mod.start()
        time.sleep(4 * _TICK_S)
        st = states()
        assert st["serving_availability"] != "firing", st
        assert st["kv_audit_clean"] == "inactive", st
        print("  baseline quiet OK")

        # -- engineered overload: slow prefill (TTFT >> 1s objective)
        # + watermark flood (availability burns on rejections)
        pt.set_flags({"kv_admission_watermark": 0.5,
                      "fault_spec": "llm_prefill:sleep=1500"})
        n_ok = n_rej = 0
        deadline = time.monotonic() + 150.0
        fired: set = set()
        while time.monotonic() < deadline:
            ok, rej = _drive_clients(srv.port, 6)
            n_ok += ok
            n_rej += rej
            fired = {s for s, v in states().items() if v == "firing"}
            if {"serving_availability", "serving_ttft_p99"} <= fired:
                break
        assert {"serving_availability", "serving_ttft_p99"} <= fired, \
            (fired, states())
        assert n_ok >= 1 and n_rej >= 1, (n_ok, n_rej)
        # the fast pair must be what tripped, with BOTH of its windows
        # over the 14.4 page threshold
        view = {a["slo"]: a for a in eng.alerts_view()["alerts"]}
        for name in ("serving_availability", "serving_ttft_p99"):
            fast = view[name]["windows"]["fast"]
            assert fast["over"], (name, fast)
            assert fast["short"]["burn_rate"] > 14.4, (name, fast)
            assert fast["long"]["burn_rate"] > 14.4, (name, fast)
        print(f"  overload tripped fast-burn alerts OK "
              f"({n_ok} admitted, {n_rej} rejected)")

        # -- exact error-budget arithmetic, straight from counters
        reqs = _counter_sum("serving_stream_requests_total")
        rej_total = _counter_sum("llm_admission_rejected_total")
        shed = _counter_sum("requests_shed_total")
        errs = _counter_sum("serving_stream_errors_total")
        bad = rej_total + shed + errs
        total = reqs + rej_total
        expected = 1.0 - bad / ((1.0 - 0.999) * total)
        specs = {s.name: s for s in eng.specs()}
        got = specs["serving_availability"].budget_remaining()
        assert abs(got - expected) < 1e-9, (got, expected)
        hist = m.registry().get("serving_ttft_ms")
        count = sum(s["count"] for s in hist._snapshot())
        under = sum(s["buckets"]["1000.0"] for s in hist._snapshot())
        exp_ttft = 1.0 - (count - under) / ((1.0 - 0.99) * count)
        got_ttft = specs["serving_ttft_p99"].budget_remaining()
        assert abs(got_ttft - exp_ttft) < 1e-9, (got_ttft, exp_ttft)
        print(f"  budget math exact OK (availability {got:+.4f}, "
              f"ttft {got_ttft:+.4f})")

        # -- load stops: the short windows drain and both alerts
        # resolve (the whole point of the multi-window pairs).
        # Shrinking the scale further compresses the aging: the slow
        # pair's 30 m short window would otherwise hold the rejection
        # burst for 18 drill-seconds; at 0.002 every window drains in
        # well under 2 s of CI time (lifetime budget math unaffected).
        pt.set_flags({"fault_spec": "",
                      "kv_admission_watermark": 0.0,
                      "slo_window_scale": _SCALE / 5.0})
        deadline = time.monotonic() + 90.0
        while time.monotonic() < deadline:
            st = states()
            if (st["serving_availability"] != "firing"
                    and st["serving_ttft_p99"] != "firing"):
                break
            time.sleep(0.25)
        assert st["serving_availability"] != "firing", st
        assert st["serving_ttft_p99"] != "firing", st
        # resolution is an explicit state transition, not a silent flap
        hist_by_slo = {a["slo"]: a["history"]
                       for a in eng.alerts_view()["alerts"]}
        for name in ("serving_availability", "serving_ttft_p99"):
            tos = [t["to"] for t in hist_by_slo[name]]
            assert "firing" in tos and "resolved" in tos, (name, tos)
        print("  alerts resolved after load stopped OK")

        # -- every transition rode the flight recorder
        ev = [e for e in flight.recorder().events()
              if e.get("kind") == "slo_alert"]
        for name in ("serving_availability", "serving_ttft_p99"):
            mine = [e for e in ev if e.get("slo") == name]
            assert any(e["to_state"] == "firing" for e in mine), name
            assert any(e["to_state"] == "resolved" for e in mine), name
        print(f"  flight recorder has {len(ev)} slo_alert event(s) OK")
    finally:
        srv.stop()

    # -- 200-stream flood: both new rings provably bounded ------------
    pt.set_flags({"tsdb_ring": 32})
    try:
        eng2 = LLMEngine(model, block_size=4, pool_blocks=64)
        for i in range(200):
            eng2.add_request(np.arange(1 + i % 7, 5 + i % 7,
                                       dtype=np.int32),
                             max_new_tokens=2, trace_id=5000 + i)
        for _ in range(2000):
            if not eng2.active():
                break
            eng2.step()
        assert not eng2.active(), "flood did not drain"
        # force well past capacity so the bound proven is the deque's,
        # not an artifact of the flood's duration
        for _ in range(40):
            tsdb_mod.sample_once()
        stats = ring.stats()
        assert stats["capacity"] == 32, stats
        assert stats["samples"], stats
        assert all(n <= 32 for n in stats["samples"].values()), stats
        assert max(stats["samples"].values()) == 32, stats
        for a in eng.alerts_view()["alerts"]:
            assert len(a["history"]) <= slo_mod.TRANSITION_CAP
        assert eng2.allocator.num_used == 0, "KV leak under flood"
        eng2.allocator.check()
        eng2._audit()
        print(f"  flood bounding OK (tsdb ring <= 32 samples/series "
              f"over {stats['series']} series, transition rings <= "
              f"{slo_mod.TRANSITION_CAP})")
    finally:
        tsdb_mod.stop()
        pt.set_flags({"tsdb_ring": 512, "slo_window_scale": 1.0,
                      "tsdb_interval_s": 1.0})

    render(load_local())
    print("self-test OK")
    return 0


# ----------------------------------------------------------------- main

def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        description="render the SLO / burn-rate alert table from a "
                    "live exporter, a JSON dump, or the in-process "
                    "engine")
    ap.add_argument("--url", help="exporter base URL "
                                  "(http://host:port)")
    ap.add_argument("--input", help="JSON file: {alerts:..., slo:...} "
                                    "or a bare /alerts dump")
    ap.add_argument("--json", action="store_true",
                    help="print the merged JSON view instead of the "
                         "table")
    ap.add_argument("--self-test", action="store_true")
    args = ap.parse_args(argv)
    if args.self_test:
        return self_test()
    if args.url:
        view = load_url(args.url)
    elif args.input:
        view = load_file(args.input)
    else:
        view = load_local()
    if args.json:
        print(json.dumps(view, indent=1, sort_keys=True, default=str))
        return 0
    return render(view)


if __name__ == "__main__":
    sys.exit(main())
