#!/usr/bin/env python3
"""ptlint — the repo's pass-based static-analysis driver.

Runs the ``paddle_tpu/analysis/`` pass registry (trace-purity,
callback-cache, lock-discipline, clock-hygiene, silent-failure,
flag-freeze, plus the migrated flags-doc / metrics-doc checkers) over
the Python tree.  Pure stdlib, no jax: the analysis package is loaded
standalone so importing it never drags the framework in — the whole
run takes milliseconds, like the doc checkers it absorbed.

Usage:
  python tools/ptlint.py --all              lint paddle_tpu/ (CI mode)
  python tools/ptlint.py --all --self-test  also run pass fixtures
  python tools/ptlint.py path/to/file.py …  lint specific files/dirs
  python tools/ptlint.py --list             print the rule catalog
  python tools/ptlint.py --all --json       machine-readable findings

Exit 0 iff zero unsuppressed findings and the baseline is healthy
(every entry has a reason and still matches — the baseline may only
shrink).  Suppression syntax and policy: docs/static_analysis.md.
"""

from __future__ import annotations

import argparse
import importlib.util
import json
import os
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BASELINE = os.path.join(ROOT, "tools", "ptlint_baseline.json")
DEFAULT_SCAN = ("paddle_tpu",)


def load_analysis():
    """Import paddle_tpu/analysis as a standalone package.

    Going through ``import paddle_tpu.analysis`` would execute
    ``paddle_tpu/__init__.py`` and pull in jax; loading the package by
    path keeps the no-framework-import contract."""
    if "pt_analysis" in sys.modules:
        return sys.modules["pt_analysis"]
    pkg = os.path.join(ROOT, "paddle_tpu", "analysis")
    spec = importlib.util.spec_from_file_location(
        "pt_analysis", os.path.join(pkg, "__init__.py"),
        submodule_search_locations=[pkg])
    mod = importlib.util.module_from_spec(spec)
    sys.modules["pt_analysis"] = mod
    spec.loader.exec_module(mod)
    return mod


ANALYSIS = load_analysis()


def run_lint(paths=None, json_out=False, baseline_path=BASELINE,
             root=ROOT, out=sys.stdout, err=sys.stderr) -> int:
    base = ANALYSIS.base
    explicit = bool(paths)
    if explicit:
        subdirs = [os.path.relpath(os.path.abspath(p), root)
                   for p in paths]
    else:
        subdirs = DEFAULT_SCAN
    parse_errors = []
    modules = base.load_modules(
        root, subdirs,
        on_error=lambda p, e: parse_errors.append(f"{p}: {e}"))
    ctx = base.Context(root=root)
    passes = ANALYSIS.all_passes()
    findings = []
    for p in passes:
        findings.extend(p.run(modules, ctx))
    by_rel = {m.rel: m for m in modules}
    active, suppressed = base.apply_suppressions(
        findings, by_rel, {p.name: p for p in passes})
    entries, errors = base.load_baseline(baseline_path)
    # with an explicit path subset, entries for unscanned files are not
    # stale — skip the shrink check
    active, baselined, berrors = base.apply_baseline(
        active, entries, by_rel, check_stale=not explicit)
    errors = parse_errors + errors + berrors
    active.sort(key=lambda f: (f.path, f.line, f.rule))
    if json_out:
        print(json.dumps({
            "findings": [vars(f) for f in active],
            "suppressed": len(suppressed),
            "baselined": len(baselined),
            "errors": errors,
        }, indent=2), file=out)
    else:
        for f in active:
            print(f.format(), file=err)
        for e in errors:
            print(f"ptlint: {e}", file=err)
        if active or errors:
            print(f"ptlint: {len(active)} finding(s), "
                  f"{len(errors)} error(s) over {len(modules)} files",
                  file=err)
        else:
            print(f"ptlint: OK ({len(passes)} passes, {len(modules)} "
                  f"files, {len(suppressed)} suppressed, "
                  f"{len(baselined)} baselined)", file=out)
    return 1 if (active or errors) else 0


def run_self_test(out=sys.stdout, err=sys.stderr) -> int:
    passes = ANALYSIS.all_passes()
    errs = []
    for p in passes:
        errs.extend(p.self_test())
    for e in errs:
        print(f"ptlint self-test: {e}", file=err)
    if errs:
        print(f"ptlint self-test: {len(errs)} failure(s)", file=err)
        return 1
    print(f"ptlint self-test: OK ({len(passes)} passes)", file=out)
    return 0


def run_list(out=sys.stdout) -> int:
    for p in ANALYSIS.all_passes():
        extra = " [suppression requires a reason]" \
            if p.requires_reason else ""
        print(f"{p.name:16s} {p.help}{extra}", file=out)
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="ptlint", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("--all", action="store_true",
                    help="lint the default tree (paddle_tpu/)")
    ap.add_argument("--self-test", action="store_true",
                    help="run every pass's positive/negative fixtures")
    ap.add_argument("--list", action="store_true",
                    help="print the rule catalog")
    ap.add_argument("--json", action="store_true",
                    help="emit findings as JSON")
    ap.add_argument("--baseline", default=BASELINE,
                    help="baseline file (default tools/ptlint_baseline.json)")
    ap.add_argument("paths", nargs="*",
                    help="specific files/directories to lint")
    args = ap.parse_args(argv)

    if args.list:
        return run_list()
    rc = 0
    ran = False
    if args.self_test:
        ran = True
        rc = max(rc, run_self_test())
    if args.all or args.paths:
        ran = True
        rc = max(rc, run_lint(paths=args.paths or None,
                              json_out=args.json,
                              baseline_path=args.baseline))
    if not ran:
        ap.print_usage(sys.stderr)
        return 2
    return rc


if __name__ == "__main__":
    sys.exit(main())
