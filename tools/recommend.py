"""Derive recommended bench/flag defaults from capture artifacts.

Reads the CAPTURE_*.json files produced by tools/capture_all.py and
prints, for every A/B the diag plan encodes, the measured winner and
the concrete default it implies (bench candidate order, flag value).
Purely a reporting tool — it changes nothing; the builder applies the
recommendations by hand so each flip lands with its evidence quoted.

Usage: python tools/recommend.py
"""

from __future__ import annotations

import os
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)
from bench import capture_value  # noqa: E402 (one shared reader)


def load(stage):
    # reporting tool: show artifacts from any device (the bench itself
    # only auto-applies same-device measurements)
    return capture_value(stage, any_device=True)


def load_field(stage, field):
    return capture_value(stage, any_device=True, field=field)


def tok(stage):
    return load(stage)  # tokens/sec (higher better)


def main() -> None:
    rows = []

    def partial_tag(*stages):
        """' [PARTIAL: x,y]' when any stage's artifact is a timed-out
        best-so-far — EVERY row carries the provenance caveat, not
        just the flash ones."""
        p = [s for s in stages if load_field(s, "partial")]
        return f" [PARTIAL artifact: {', '.join(p)}]" if p else ""

    def compare(name, a_stage, b_stage, a_label, b_label,
                implies_fmt, field="value"):
        a = load_field(a_stage, field)
        b_ = load_field(b_stage, field)
        if a is None or b_ is None:
            missing = [s for s, v in ((a_stage, a), (b_stage, b_))
                       if v is None]
            rows.append((name, f"PENDING (missing {missing})", ""))
            return None
        win, lose = (a_label, b_label) if a >= b_ else (b_label, a_label)
        ratio = max(a, b_) / max(min(a, b_), 1e-9)
        fmt = ".0f" if field == "value" else ".3f"
        rows.append((name, f"{win} wins {ratio:.2f}x "
                     f"({a_label}={a:{fmt}} vs {b_label}={b_:{fmt}}"
                     f"{'' if field == 'value' else ' ' + field})"
                     f"{partial_tag(a_stage, b_stage)}",
                     implies_fmt.format(win=win)))
        return win

    # ---- flash-era ladder: per-batch values come from bench's OWN
    # evidence reader (bert_batch_judged / bert_batch_stages), so each
    # VALUE matches what bench would rank that batch by. The row spans
    # more batches than bench sweeps (b24/b64 are reporting-only
    # A/B points; bench's built-in batch_opts is [16, 8, 32]) and
    # reads any-device artifacts, so the lead is a recommendation to
    # apply by hand, not bench's literal runtime choice.
    from bench import bert_batch_judged, bert_batch_stages
    fvals = {b: bert_batch_judged(b, any_device=True)
             for b in (8, 16, 24, 32, 64)}
    meas = {b: v for b, v in fvals.items() if v is not None}
    if meas:
        order = sorted(meas, key=lambda b: -meas[b])
        # provenance must cover the FALLBACK stages too: when a batch
        # has no flash-era artifact, bert_batch_judged sources the
        # XLA-era pair, and a partial there must still tag the row
        all_stages = [s for b in order
                      for s in (bert_batch_stages(b)
                                + [f"bert_b{b}_perleaf_noqkv",
                                   f"bert_b{b}_maskedlm"])]
        rows.append(("BERT batch order (FLASH era, judged)",
                     " > ".join(f"b{b}={meas[b]:.4f}" for b in order)
                     + partial_tag(*all_stages),
                     f"batch ladder lead = {order[:2]} (apply to "
                     "bench batch_opts by hand)"))
    compare("flash in-model @seq512 (b8)",
            "bert_b8_flash512", "bert_b8_perleaf_noqkv",
            "flash", "xla_attn",
            "flags.flash_attention_min_seq_train = 512 iff flash wins")
    compare("BTHD layout (b8 flash)",
            "bert_b8_flash_bthd", "bert_b8_flash512",
            "bthd", "transpose",
            "flags.attention_bthd_layout default = {win}")
    compare("Pallas vs XLA LayerNorm (b8 spl8)",
            "bert_b8_flash512_spl8", "bert_b8_spl8_xlaln",
            "pallas_ln", "xla_ln",
            "flags.use_pallas_layer_norm default = {win}")
    # fused QKV at b8 (round-2 chip said -3%, round-3 HLO said better)
    compare("fused QKV projection (b8)",
            "bert_b8_perleaf_qkv", "bert_b8_perleaf_noqkv",
            "qkv_on", "qkv_off",
            "flags.fused_qkv_projection default = {win}")
    # batch scaling, per-leaf (XLA-attention era, historical)
    vals = {b: tok(f"bert_b{b}_perleaf_noqkv") for b in (8, 16, 32)}
    if all(v is not None for v in vals.values()):
        order = sorted(vals, key=lambda b: -vals[b])
        rows.append(("BERT batch order (XLA-attn era)",
                     " > ".join(f"b{b}={vals[b]:.0f}" for b in order)
                     + partial_tag(*(f"bert_b{b}_perleaf_noqkv"
                                     for b in order)),
                     "historical; flash-era order governs"))
    else:
        rows.append(("BERT batch order",
                     f"PENDING ({ {b: v for b, v in vals.items()} })",
                     ""))
    # remat
    b32 = tok("bert_b32_perleaf_noqkv")
    r32 = tok("bert_b32_remat")
    if b32 is not None and r32 is not None:
        rows.append(("transformer_remat (b32)",
                     f"{'remat' if r32 > b32 else 'no-remat'} wins "
                     f"({r32:.0f} vs {b32:.0f})"
                     + partial_tag("bert_b32_remat",
                                   "bert_b32_perleaf_noqkv"),
                     f"flags.transformer_remat default = {r32 > b32}"))
    r64 = tok("bert_b64_remat")
    if r64 is not None:
        rows.append(("remat-enabled b64",
                     f"{r64:.0f} tok/s"
                     + partial_tag("bert_b64_remat"),
                     "larger-batch headroom check"))
    # bf16 moments
    b8 = tok("bert_b8_perleaf_noqkv")
    mv = tok("bert_b8_bf16mv")
    if b8 is not None and mv is not None:
        rows.append(("optimizer_moment_dtype bf16 (b8)",
                     f"{'bf16' if mv > b8 else 'fp32'} wins "
                     f"({mv:.0f} vs {b8:.0f})"
                     + partial_tag("bert_b8_bf16mv",
                                   "bert_b8_perleaf_noqkv"),
                     "flags.optimizer_moment_dtype default = "
                     f"{'bfloat16' if mv > b8 else 'float32'}"))
    # resnet
    compare("ResNet BN single-pass (b128)",
            "resnet_bn1pass", "resnet_nhwc_b128_perleaf",
            "bn1pass", "two-pass",
            "flags.batch_norm_single_pass default = {win}")
    compare("ResNet steps-per-loop 8 (bn1pass)",
            "resnet_bn1pass_spl8", "resnet_bn1pass",
            "spl8", "spl1",
            "bench resnet default_spl = 8 iff spl8 wins")
    compare("ResNet block remat (bn1pass+spl8)",
            "resnet_remat", "resnet_bn1pass_spl8",
            "remat", "no-remat",
            "flags.resnet_block_remat default = {win}")
    floor = tok("rn50_floor")
    if floor is not None:
        rows.append(("raw-JAX RN50 floor probe",
                     f"{floor:.0f} img/s"
                     + partial_tag("rn50_floor"),
                     "framework-overhead bound (single dispatch)"))
    compare("ResNet s2d stem (b128 NHWC)",
            "resnet_nhwc_b128_s2d", "resnet_nhwc_b128_perleaf",
            "s2d", "plain",
            "flags.resnet_space_to_depth_stem default = "
            "{win}" .replace("{win}", "(s2d wins?)"))
    r256 = tok("resnet_nhwc_b256_perleaf")
    r128 = tok("resnet_nhwc_b128_perleaf")
    if r256 is not None and r128 is not None:
        rows.append(("ResNet batch 256 vs 128 (img/s)",
                     f"b256={r256:.0f} vs b128={r128:.0f}"
                     + partial_tag("resnet_nhwc_b256_perleaf",
                                   "resnet_nhwc_b128_perleaf"),
                     "bench batches order"))
    # masked-LM head restriction (reference mask_pos parity) — judged
    # by vs_baseline: masked mode's honest FLOP accounting means
    # higher tokens/sec does not imply a higher judged number
    for b in (8, 32):
        compare(f"masked-LM head (b{b})",
                f"bert_b{b}_maskedlm", f"bert_b{b}_perleaf_noqkv",
                "masked", "full",
                "bench masked_for auto-pin uses this pair",
                field="vs_baseline")
    # flash crossover: report the stage's speedup AT THE SEQ THE
    # ARTIFACT ACTUALLY RECORDS — a timed-out stage's last line is the
    # speedup at whatever seq last completed, not the top of the sweep,
    # so the metric/seq come from the parsed line instead of being
    # assumed
    for st in ("flash", "flash_train", "flash_train_t128",
               "flash_train_t512"):
        v = load(st)
        if v is not None:
            seq = load_field(st, "seq")
            metric = load_field(st, "metric") or st
            partial = load_field(st, "partial")
            # older artifacts embed the seq only in the metric string;
            # don't print it twice when both carry it
            at = f" @seq{seq}" if (seq is not None
                                   and f"@seq{seq}" not in metric) else ""
            note = " [PARTIAL artifact]" if partial else ""
            rows.append((f"{st} speedup", f"{v}x{at}{note} ({metric})",
                         "flash_attention_min_seq/_train (and "
                         "flash_block_q/k for the tile stages) from "
                         "the per-seq stderr table in the capture "
                         "artifact"))
        else:
            rows.append((f"{st}", "PENDING", ""))

    w = max(len(r[0]) for r in rows) + 2
    for name, result, implies in rows:
        line = f"{name:<{w}} {result}"
        if implies:
            line += f"   -> {implies}"
        print(line)


if __name__ == "__main__":
    main()
