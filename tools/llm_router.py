"""Front-door LLM router launcher + no-TPU self-test.

Runs ``paddle_tpu.serving_llm.router.Router`` as its own process: a
stdlib front door speaking the serving wire protocol
(docs/serving_protocol.md) that spreads streams over N
``inference.Server`` backends with health-gated rotation, circuit
breaking, and deterministic mid-stream failover
(docs/fault_tolerance.md, "Router failover taxonomy").

Usage:
    python tools/llm_router.py --backend H:P --backend H:P [--port N]
    python tools/llm_router.py --self-test       # no-TPU CI drill

A backend spec is ``host:port`` (the serving wire port) or
``host:port:healthzport`` to add exporter ``/healthz`` probing beside
the PTSC STATS probe. ``--portfile`` writes the bound router port for
scripting (the launcher idiom tools/chaos_drill.py uses).

``--self-test`` boots TWO real backend processes with identical
weights (both seed ``pt.seed(0)`` before building the model), routes
a stream through them, SIGKILLs the backend that is actively serving
it after two delivered tokens, and asserts the spliced client-visible
sequence is bitwise identical to an uninterrupted single-backend
reference at temperature 0.8 — the position-keyed-sampling failover
guarantee — with exactly one failover counted, zero retries, and a
clean KV audit on the SIGTERMed survivor.
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import subprocess
import sys
import tempfile
import threading
import time

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if ROOT not in sys.path:
    sys.path.insert(0, ROOT)


# --------------------------------------------------------------- self-test

_BACKEND_SRC = r"""
import json, sys
import paddle_tpu as pt
from paddle_tpu.inference import Server
from paddle_tpu.models import GPTLanguageModel
from paddle_tpu.serving_llm import LLMEngine

out, portfile = sys.argv[1], sys.argv[2]
# identical weights on every backend — the precondition for exact
# failover parity (a real fleet loads the same checkpoint)
pt.seed(0)
model = GPTLanguageModel()
engine = LLMEngine(model, block_size=4, pool_blocks=256)
srv = Server(None, llm_engine=engine)

def on_drained(server):
    ok = True
    try:
        engine.allocator.check()
    except AssertionError:
        ok = False
    json.dump({"kv_used": engine.allocator.num_used,
               "check_ok": ok,
               "gauges_ok": bool(engine.allocator.gauges_agree()),
               "open_streams": len(server._llm._reqs)},
              open(out, "w"))

with open(portfile, "w") as f:
    f.write(str(srv.port))
srv.serve_forever(on_drained=on_drained)
"""


def _spawn_backend(tmp: str, idx: int):
    """One backend subprocess; returns (proc, port, audit_path)."""
    script = os.path.join(tmp, f"backend_{idx}.py")
    with open(script, "w") as f:
        f.write(_BACKEND_SRC)
    audit = os.path.join(tmp, f"audit_{idx}.json")
    portfile = os.path.join(tmp, f"port_{idx}.txt")
    env = dict(os.environ)
    env.update({"JAX_PLATFORMS": "cpu", "PYTHONPATH": ROOT,
                "FLAGS_enable_metrics": "1", "FLAGS_metrics_port": "-1",
                "FLAGS_serving_drain_deadline_s": "5.0"})
    proc = subprocess.Popen([sys.executable, script, audit, portfile],
                            env=env, stdout=subprocess.PIPE,
                            stderr=subprocess.PIPE, text=True)
    return proc, portfile, audit


def _wait_port(proc, portfile: str, timeout_s: float = 180.0) -> int:
    deadline = time.time() + timeout_s
    while time.time() < deadline:
        if os.path.exists(portfile):
            return int(open(portfile).read())
        if proc.poll() is not None:
            raise AssertionError(
                f"backend died during startup:\n{proc.communicate()[1]}")
        time.sleep(0.1)
    raise AssertionError("backend never bound its port")


def self_test() -> int:
    """Kill-one-of-two mid-stream; the client must not notice."""
    import numpy as np
    import paddle_tpu as pt
    from paddle_tpu.inference import Client
    from paddle_tpu.serving_llm.router import Router

    pt.set_flags({"enable_metrics": True, "metrics_port": -1,
                  "router_retry_backoff_s": 0.0,
                  "router_probe_interval_s": 0.3})
    tmp = tempfile.mkdtemp(prefix="llm_router_selftest_")
    procs = []
    router = None
    try:
        pa, pfa, audit_a = _spawn_backend(tmp, 0)
        pb, pfb, audit_b = _spawn_backend(tmp, 1)
        procs = [pa, pb]
        port_a = _wait_port(pa, pfa)
        port_b = _wait_port(pb, pfb)
        print(f"backends up: {port_a} {port_b}", flush=True)

        prompt = (np.arange(8, dtype=np.int32) * 3) % 64
        gen_kw = dict(max_new_tokens=24, temperature=0.8, seed=7)

        # uninterrupted single-backend reference (backend A)
        with Client(port=port_a, timeout_s=120.0,
                    deadline_s=120.0) as cli:
            ref = cli.generate(prompt, **gen_kw).tolist()
            ref0 = cli.generate(prompt, max_new_tokens=8,
                                temperature=0.0).tolist()
        assert len(ref) == 24, ref
        print(f"reference tokens: {ref}", flush=True)

        router = Router([("127.0.0.1", port_a), ("127.0.0.1", port_b)],
                        probe_interval_s=0.3).start()
        print(f"router up: {router.port}", flush=True)

        # stream through the router; SIGKILL the serving backend
        # after two delivered tokens
        got = []
        with Client(port=router.port, timeout_s=120.0,
                    deadline_s=120.0) as cli:
            for i, chunk in enumerate(cli.generate_stream(
                    prompt, **gen_kw)):
                got.extend(int(t) for t in np.asarray(chunk).ravel())
                if i == 1:
                    snap = router.snapshot()
                    busy = [b["name"] for b in snap["backends"]
                            if b["streams_active"] > 0]
                    assert len(busy) == 1, snap
                    victim_port = int(busy[0].rsplit(":", 1)[1])
                    victim = pa if victim_port == port_a else pb
                    victim.send_signal(signal.SIGKILL)
                    print(f"SIGKILLed backend :{victim_port} after "
                          f"{len(got)} tokens", flush=True)
            assert got == ref, (got, ref)
            print("failover parity OK (temperature 0.8)", flush=True)

            snap = router.snapshot()
            assert snap["failovers_total"] == 1, snap
            assert snap["retries_total"] == 0, snap
            assert snap["shed_total"] == 0, snap

            # survivor still serves; temp-0 parity across processes
            # proves the seeded weights really are identical
            out0 = cli.generate(prompt, max_new_tokens=8,
                                temperature=0.0).tolist()
            assert out0 == ref0, (out0, ref0)
            print("survivor parity OK (temperature 0)", flush=True)

        victim.wait(10)
        survivor = pb if victim is pa else pa
        survivor_audit = audit_b if victim is pa else audit_a

        # SIGTERM the survivor: graceful drain, then a clean KV audit
        survivor.send_signal(signal.SIGTERM)
        rc = survivor.wait(60)
        assert rc == -signal.SIGTERM, rc
        audit = json.load(open(survivor_audit))
        assert audit["kv_used"] == 0, audit
        assert audit["check_ok"] and audit["gauges_ok"], audit
        assert audit["open_streams"] == 0, audit
        print(f"survivor audit clean: {audit}", flush=True)
    finally:
        if router is not None:
            router.stop()
        for p in procs:
            if p.poll() is None:
                p.send_signal(signal.SIGKILL)
        for p in procs:
            try:
                p.wait(10)
            except subprocess.TimeoutExpired:
                pass
    print("self-test OK")
    return 0


# -------------------------------------------------------------- launcher


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="front-door router over N LLM serving backends "
                    "(health-gated rotation, circuit breaking, "
                    "deterministic mid-stream failover)")
    ap.add_argument("--backend", action="append", default=[],
                    metavar="HOST:PORT[:HEALTHZPORT]",
                    help="serving backend (repeatable; optional third "
                         "field = exporter port for /healthz probes)")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=0,
                    help="router listen port (0 = ephemeral)")
    ap.add_argument("--probe-interval", type=float, default=None,
                    metavar="S", help="backend probe period "
                    "(default FLAGS_router_probe_interval_s)")
    ap.add_argument("--portfile", default=None,
                    help="write the bound port here once listening")
    ap.add_argument("--self-test", action="store_true")
    args = ap.parse_args(argv)
    if args.self_test:
        return self_test()
    if not args.backend:
        ap.error("at least one --backend required (or --self-test)")

    from paddle_tpu.serving_llm.router import Router
    router = Router(args.backend, host=args.host, port=args.port,
                    probe_interval_s=args.probe_interval).start()
    print(f"llm_router: listening on {router.addr}, "
          f"{len(router.pool.backends)} backend(s)", flush=True)
    if args.portfile:
        with open(args.portfile, "w") as f:
            f.write(str(router.port))

    stop = threading.Event()

    def _sig(signum, frame):
        stop.set()

    signal.signal(signal.SIGTERM, _sig)
    signal.signal(signal.SIGINT, _sig)
    try:
        while not stop.is_set():
            stop.wait(0.5)
    finally:
        router.stop()
        print("llm_router: stopped", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
